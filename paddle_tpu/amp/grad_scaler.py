"""GradScaler — dynamic loss scaling, parity with
dygraph/amp/loss_scaler.py:27 + operators/amp/update_loss_scaling_op.
On TPU with bfloat16 this is a no-op passthrough (enable=False default when
dtype is bf16); kept fully functional for fp16 parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, no_grad, wrap_raw

__all__ = ["AmpScaler", "GradScaler"]


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        found = False
        inv = 1.0 / self._scale
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._value.astype(jnp.float32) * inv
            if not bool(jnp.all(jnp.isfinite(g))):
                found = True
            p.grad = wrap_raw(g.astype(p.grad._value.dtype))
        self._found_inf = found

    def minimize(self, optimizer, scaled_loss, *args, **kwargs):
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update()
        optimizer.clear_grad()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not getattr(self, "_unscaled", False):
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False

    def update(self):
        if self._enable:
            self._update()

    def _update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, d):
        self._scale = d.get("scale", self._scale)
        self._good_steps = d.get("good_steps", 0)
        self._bad_steps = d.get("bad_steps", 0)


class GradScaler(AmpScaler):
    def get_loss_scaling(self):
        return wrap_raw(jnp.asarray(self._scale, jnp.float32))
