"""GradScaler — dynamic loss scaling, parity with
dygraph/amp/loss_scaler.py:27 + operators/amp/update_loss_scaling_op.
On TPU with bfloat16 this is a no-op passthrough (enable=False default when
dtype is bf16); kept fully functional for fp16 parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, no_grad, wrap_raw

__all__ = ["AmpScaler", "GradScaler", "current_loss_scale"]

# last scale any live scaler holds — read by core.sanitizer so a
# non-finite abort can report the scale in effect without plumbing the
# scaler through every engine
_last_scale = None


def current_loss_scale():
    """The most recently set loss scale of any enabled AmpScaler in this
    process, or None when AMP scaling is not in play."""
    return _last_scale


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        if enable:
            self._publish_scale()
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        found = False
        inv = 1.0 / self._scale
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._value.astype(jnp.float32) * inv
            if not bool(jnp.all(jnp.isfinite(g))):
                found = True
            p.grad = wrap_raw(g.astype(p.grad._value.dtype))
        self._found_inf = found

    def minimize(self, optimizer, scaled_loss, *args, **kwargs):
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update()
        optimizer.clear_grad()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not getattr(self, "_unscaled", False):
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False

    def update(self):
        if self._enable:
            self._update()

    def _publish_scale(self):
        global _last_scale
        _last_scale = self._scale

    def _update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._publish_scale()

    def backoff(self, factor=None, min_scale=1.0):
        """Out-of-band scale decrease (resilience StepGuard contract):
        a non-finite COMPILED step was detected outside this scaler's
        own unscale_ sweep — treat it like a found_inf event: shrink the
        scale (``factor`` defaults to ``decr_ratio``) and restart the
        good-step growth clock. A no-op for static scales
        (``use_dynamic_loss_scaling=False``), same as ``_update``."""
        if not self._enable or not self._dynamic:
            return self._scale
        f = self._decr_ratio if factor is None else float(factor)
        self._scale = max(self._scale * f, float(min_scale))
        self._good_steps = 0
        self._bad_steps = 0
        self._publish_scale()
        return self._scale

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)
        if self._enable:
            self._publish_scale()

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, d):
        # restore EVERY key state_dict() emits — dropping the
        # incr/decr schedule knobs silently reset a resumed job's
        # scaling cadence to constructor defaults
        self._scale = float(d.get("scale", self._scale))
        self._incr_ratio = float(d.get("incr_ratio", self._incr_ratio))
        self._decr_ratio = float(d.get("decr_ratio", self._decr_ratio))
        self._incr_every_n_steps = int(
            d.get("incr_every_n_steps", self._incr_every_n_steps))
        self._decr_every_n = int(
            d.get("decr_every_n_nan_or_inf", self._decr_every_n))
        self._good_steps = int(d.get("good_steps", 0))
        self._bad_steps = int(d.get("bad_steps", 0))
        if self._enable:
            self._publish_scale()


class GradScaler(AmpScaler):
    def get_loss_scaling(self):
        return wrap_raw(jnp.asarray(self._scale, jnp.float32))
