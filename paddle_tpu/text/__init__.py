"""paddle_tpu.text — text datasets (parity python/paddle/text/datasets/).

Zero-egress: datasets read local files when given (the reference's archive
formats), else produce deterministic synthetic corpora so language-model
pipelines run end-to-end offline. See ``datasets.py`` for the full set.
"""
from __future__ import annotations

import numpy as np

from ..io.dataset import Dataset
from .datasets import (  # noqa: F401
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
    UCIHousing,
    WMT14,
    WMT16,
)

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "FakeTextDataset", "viterbi_decode"]


class FakeTextDataset(Dataset):
    """Deterministic synthetic token sequences for LM training/benchmarks."""

    def __init__(self, num_samples=2048, seq_len=128, vocab_size=50257, seed=0):
        self.num_samples = num_samples
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        # zipf-ish distribution mimics natural token frequencies
        toks = rng.zipf(1.1, size=self.seq_len + 1) % self.vocab_size
        return toks[:-1].astype(np.int64), toks[1:].astype(np.int64)

    def __len__(self):
        return self.num_samples


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF viterbi decoding (parity paddle.text.viterbi_decode)."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor, wrap_raw

    pots = potentials.numpy() if isinstance(potentials, Tensor) else np.asarray(potentials)
    trans = (
        transition_params.numpy()
        if isinstance(transition_params, Tensor)
        else np.asarray(transition_params)
    )
    b, t, n = pots.shape
    scores = np.zeros((b,), np.float32)
    paths = np.zeros((b, t), np.int64)
    for bi in range(b):
        dp = pots[bi, 0].copy()
        back = np.zeros((t, n), np.int64)
        for ti in range(1, t):
            cand = dp[:, None] + trans
            back[ti] = cand.argmax(axis=0)
            dp = cand.max(axis=0) + pots[bi, ti]
        best = int(dp.argmax())
        scores[bi] = dp[best]
        seq = [best]
        for ti in range(t - 1, 0, -1):
            best = int(back[ti, best])
            seq.append(best)
        paths[bi] = np.asarray(seq[::-1])
    return wrap_raw(jnp.asarray(scores)), wrap_raw(jnp.asarray(paths))
