"""paddle_tpu.text — text datasets (parity python/paddle/text/datasets/).

Zero-egress: datasets read local files when given, else produce deterministic
synthetic corpora so language-model pipelines run end-to-end offline.
"""
from __future__ import annotations

import os

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "WMT14", "FakeTextDataset", "viterbi_decode"]


class FakeTextDataset(Dataset):
    """Deterministic synthetic token sequences for LM training/benchmarks."""

    def __init__(self, num_samples=2048, seq_len=128, vocab_size=50257, seed=0):
        self.num_samples = num_samples
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        # zipf-ish distribution mimics natural token frequencies
        toks = rng.zipf(1.1, size=self.seq_len + 1) % self.vocab_size
        return toks[:-1].astype(np.int64), toks[1:].astype(np.int64)

    def __len__(self):
        return self.num_samples


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150):
        self.mode = mode
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 512 if mode == "train" else 128
        self.docs = [rng.randint(1, 5000, size=rng.randint(20, 200)).astype(np.int64)
                     for _ in range(n)]
        self.labels = rng.randint(0, 2, size=n).astype(np.int64)
        self.word_idx = {i: i for i in range(5000)}

    def __getitem__(self, idx):
        return self.docs[idx], np.int64(self.labels[idx])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        self.window_size = window_size
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 1024 if mode == "train" else 256
        self.samples = [rng.randint(0, 2000, size=window_size).astype(np.int64)
                        for _ in range(n)]
        self.word_idx = {i: i for i in range(2000)}

    def __getitem__(self, idx):
        s = self.samples[idx]
        return tuple(s[:-1]), s[-1]

    def __len__(self):
        return len(self.samples)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train"):
        if data_file and os.path.exists(data_file):
            data = np.loadtxt(data_file)
        else:
            rng = np.random.RandomState(3)
            x = rng.rand(506, 13).astype(np.float32)
            y = (x @ rng.rand(13).astype(np.float32))[:, None] + 0.1
            data = np.concatenate([x, y], axis=1)
        split = int(len(data) * 0.8)
        self.data = data[:split] if mode == "train" else data[split:]

    def __getitem__(self, idx):
        row = self.data[idx].astype(np.float32)
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class WMT14(Dataset):
    def __init__(self, data_file=None, mode="train", dict_size=30000):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 256 if mode == "train" else 64
        self.samples = [
            (rng.randint(2, dict_size, size=rng.randint(5, 30)).astype(np.int64),
             rng.randint(2, dict_size, size=rng.randint(5, 30)).astype(np.int64))
            for _ in range(n)
        ]

    def __getitem__(self, idx):
        src, tgt = self.samples[idx]
        return src, tgt[:-1], tgt[1:]

    def __len__(self):
        return len(self.samples)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF viterbi decoding (parity paddle.text.viterbi_decode)."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor, wrap_raw

    pots = potentials.numpy() if isinstance(potentials, Tensor) else np.asarray(potentials)
    trans = (
        transition_params.numpy()
        if isinstance(transition_params, Tensor)
        else np.asarray(transition_params)
    )
    b, t, n = pots.shape
    scores = np.zeros((b,), np.float32)
    paths = np.zeros((b, t), np.int64)
    for bi in range(b):
        dp = pots[bi, 0].copy()
        back = np.zeros((t, n), np.int64)
        for ti in range(1, t):
            cand = dp[:, None] + trans
            back[ti] = cand.argmax(axis=0)
            dp = cand.max(axis=0) + pots[bi, ti]
        best = int(dp.argmax())
        scores[bi] = dp[best]
        seq = [best]
        for ti in range(t - 1, 0, -1):
            best = int(back[ti, best])
            seq.append(best)
        paths[bi] = np.asarray(seq[::-1])
    return wrap_raw(jnp.asarray(scores)), wrap_raw(jnp.asarray(paths))
