"""Linear-chain CRF ops — sequence-labeling training and Viterbi decoding.

Capability parity with the reference's CRF operator pair
(/root/reference/paddle/fluid/operators/linear_chain_crf_op.cc,.h and
crf_decoding_op.cc): ``transition`` is the reference's ``[num_tags+2,
num_tags]`` learnable layout — row 0 holds the start weights :math:`a`,
row 1 the end weights :math:`b`, rows 2.. the tag→tag weights :math:`w`
(linear_chain_crf_op.h:180-183) — and ``linear_chain_crf`` returns the same
per-sequence cost :math:`\\log Z - \\mathrm{score}(s)` the reference's
ForwardOneSequence computes (linear_chain_crf_op.h:166-225).

TPU-first design deltas:
- sequences are **padded + lengths** (the repo-wide ragged representation,
  tensor/sequence.py) instead of LoDTensor offsets; every op is pure jnp
  with static shapes, jittable and vmappable.
- the forward algorithm runs in **log space as a lax.scan** (logsumexp
  recurrence) instead of the reference's L1-normalized product recurrence —
  same math, but an O(S) scan of [B, D, D] adds that XLA vectorizes, and
  autodiff through the scan REPLACES the hand-written backward kernel
  (linear_chain_crf_grad): gradients w.r.t. emission and transition come
  from jax.grad.
- Viterbi runs as a forward scan carrying [B, D] scores + backpointers and
  a reverse scan for path extraction (crf_decoding_op.h's two loops, as
  scans).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply_op

__all__ = ["linear_chain_crf", "crf_decoding"]


def _norm_inputs(emission, label, length):
    """Canonicalize to emission [B,S,D], label [B,S] int32, length [B]."""
    if emission.ndim == 2:  # single sequence [S, D]
        emission = emission[None]
        if label is not None:
            label = label[None]
    if label is not None:
        if label.ndim == emission.ndim:  # trailing [.., 1]
            label = jnp.squeeze(label, axis=-1)
        label = label.astype(jnp.int32)
    if length is not None:
        length = jnp.reshape(length, (-1,)).astype(jnp.int32)
    else:
        length = jnp.full((emission.shape[0],), emission.shape[1], jnp.int32)
    return emission, label, length


def linear_chain_crf(emission, label, transition, length=None):
    """Per-sequence CRF cost ``log Z - score(label)``, shape [B, 1].

    ``emission``: [B, S, D] (or [S, D]) unscaled emission weights.
    ``label``: [B, S] (or [B, S, 1]) int tags.
    ``transition``: [D+2, D] — rows 0/1 are start/end weights, rows 2..
    the tag→tag transition matrix (reference layout).
    ``length``: [B] valid lengths (None → all S).

    Differentiable w.r.t. ``emission`` and ``transition``.
    """
    def f(em, lbl, trans, *rest):
        ln = rest[0] if rest else None
        em, lbl, ln = _norm_inputs(em, lbl, ln)
        B, S, D = em.shape
        a = trans[0]          # start weights
        b = trans[1]          # end weights
        w = trans[2:]         # [D, D] from-tag × to-tag
        t_idx = jnp.arange(S)

        # ---- partition function: log-space forward algorithm ----
        alpha0 = a[None, :] + em[:, 0]                      # [B, D]

        def fwd(alpha, t):
            # alpha' = logsumexp_j(alpha_j + w[j, i]) + x_t[i], frozen at pad
            nxt = jax.nn.logsumexp(alpha[:, :, None] + w[None], axis=1)
            nxt = nxt + em[:, t]
            keep = (t < ln)[:, None]
            return jnp.where(keep, nxt, alpha), None

        alpha, _ = jax.lax.scan(fwd, alpha0, t_idx[1:]) if S > 1 else (alpha0, None)
        log_z = jax.nn.logsumexp(alpha + b[None, :], axis=1)  # [B]

        # ---- score of the gold path ----
        valid = t_idx[None, :] < ln[:, None]                  # [B, S]
        picked = jnp.take_along_axis(em, lbl[..., None], axis=2)[..., 0]
        score = jnp.sum(jnp.where(valid, picked, 0.0), axis=1)
        score = score + a[lbl[:, 0]]
        last = jnp.clip(ln - 1, 0, S - 1)
        last_tag = jnp.take_along_axis(lbl, last[:, None], axis=1)[:, 0]
        score = score + b[last_tag]
        if S > 1:
            tr = w[lbl[:, :-1], lbl[:, 1:]]                   # [B, S-1]
            tvalid = t_idx[None, 1:] < ln[:, None]
            score = score + jnp.sum(jnp.where(tvalid, tr, 0.0), axis=1)
        return (log_z - score)[:, None]

    def detached(x):
        return x.detach() if isinstance(x, Tensor) else jnp.asarray(x)

    # label/length are integer inputs — detach so only emission/transition
    # participate in the recorded vjp
    args = ((emission, detached(label), transition)
            + ((detached(length),) if length is not None else ()))
    return apply_op(f, *args)


def crf_decoding(emission, transition, label=None, length=None):
    """Viterbi decoding with the learned CRF ``transition``.

    Without ``label``: the most-likely tag path, [B, S] int64 (padded
    positions 0). With ``label`` (training-time, feeds chunk_eval like the
    reference): a [B, S] 0/1 tensor — 1 where the decoded tag equals the
    gold tag (crf_decoding_op.cc:66-74).
    """
    def f(em, trans, *rest):
        rest = list(rest)
        lb = rest.pop(0) if label is not None else None
        l_ = rest.pop(0) if length is not None else None
        em, lb, l_ = _norm_inputs(em, lb, l_)
        B, S, D = em.shape
        a, b, w = trans[0], trans[1], trans[2:]
        t_idx = jnp.arange(S)

        dp0 = a[None, :] + em[:, 0]
        # end weights join at each row's last valid step
        dp0 = dp0 + jnp.where((l_ == 1)[:, None], b[None, :], 0.0)

        def fwd(dp, t):
            cand = dp[:, :, None] + w[None]                  # [B, from, to]
            bp = jnp.argmax(cand, axis=1)                    # [B, D]
            nxt = jnp.max(cand, axis=1) + em[:, t]
            nxt = nxt + jnp.where((t == l_ - 1)[:, None], b[None, :], 0.0)
            keep = (t < l_)[:, None]
            dp = jnp.where(keep, nxt, dp)
            # frozen steps point back at themselves so the backtrace walks
            # through padding unchanged
            bp = jnp.where(keep, bp, jnp.arange(D)[None, :])
            return dp, bp

        if S > 1:
            dp, bps = jax.lax.scan(fwd, dp0, t_idx[1:])      # bps [S-1, B, D]
        else:
            dp, bps = dp0, jnp.zeros((0, B, D), jnp.int32)
        best = jnp.argmax(dp, axis=1)                        # [B]

        def back(tag, bp):
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, tag

        first, tags = jax.lax.scan(back, best, bps, reverse=True)
        path = jnp.concatenate([first[None], tags], axis=0).T  # [B, S]
        valid = t_idx[None, :] < l_[:, None]
        path = jnp.where(valid, path, 0).astype(jnp.int64)
        if lb is not None:
            ok = (path == lb.astype(jnp.int64)) & valid
            return ok.astype(jnp.int64)
        return path

    def stopped(x):
        return x.detach() if isinstance(x, Tensor) else jnp.asarray(x)

    # decoding is not differentiable — detach everything
    args = [stopped(emission), stopped(transition)]
    if label is not None:
        args.append(stopped(label))
    if length is not None:
        args.append(stopped(length))
    return apply_op(f, *args)
