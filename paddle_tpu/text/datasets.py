"""Text datasets — parity with python/paddle/text/datasets/ (imdb.py,
imikolov.py, movielens.py, uci_housing.py, conll05.py, wmt14.py, wmt16.py).

Zero-egress environment: each dataset loads from a local ``data_file`` when
one is supplied (same archive/text formats the reference downloads);
otherwise a deterministic synthetic corpus with the same sample structure is
generated so pipelines, tests, and examples run without network access.
Sample tuple shapes/dtypes match the reference exactly.
"""
from __future__ import annotations

import gzip
import os
import re
import string
import tarfile
from collections import Counter

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05st",
           "WMT14", "WMT16"]


# ---------------------------------------------------------------------------
# deterministic synthetic corpus machinery
# ---------------------------------------------------------------------------
_WORDS = [
    "the", "a", "film", "movie", "great", "bad", "plot", "acting", "story",
    "good", "terrible", "wonderful", "boring", "fun", "slow", "fast", "hero",
    "villain", "scene", "music", "score", "director", "cast", "ending",
    "beginning", "character", "dialogue", "visuals", "effects", "script",
]

_POS = ["great", "good", "wonderful", "fun", "hero"]
_NEG = ["bad", "terrible", "boring", "slow", "villain"]


def _synthetic_docs(n, seed, label_correlated=True):
    """Deterministic token documents; sentiment words correlate with label."""
    rng = np.random.RandomState(seed)
    docs, labels = [], []
    for i in range(n):
        lab = int(rng.randint(0, 2))
        ln = int(rng.randint(8, 40))
        words = [
            _WORDS[rng.randint(0, len(_WORDS))] for _ in range(ln)
        ]
        bias = _POS if lab else _NEG
        for _ in range(max(2, ln // 6)):
            words[rng.randint(0, ln)] = bias[rng.randint(0, len(bias))]
        docs.append(words)
        labels.append(lab)
    return docs, labels


def _build_word_dict(docs, cutoff=1):
    cnt = Counter(w for d in docs for w in d)
    words = sorted([w for w, c in cnt.items() if c >= cutoff],
                   key=lambda w: (-cnt[w], w))
    return {w: i for i, w in enumerate(words)}


# ---------------------------------------------------------------------------
class Imdb(Dataset):
    """IMDB sentiment (reference text/datasets/imdb.py): samples are
    (np.int64 doc token ids, np.int64 0/1 label); ``word_idx`` maps word→id
    with '<unk>' as the last id."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 num_samples=512):
        assert mode in ("train", "test")
        self.mode = mode
        if data_file and os.path.exists(data_file):
            docs, labels = self._read_tar(data_file, mode, cutoff)
        else:
            docs, labels = _synthetic_docs(
                num_samples, seed=1 if mode == "train" else 2)
            self.word_idx = _build_word_dict(docs)
        self.word_idx.setdefault("<unk>", len(self.word_idx))
        unk = self.word_idx["<unk>"]
        self.docs = [
            np.asarray([self.word_idx.get(w, unk) for w in d], np.int64)
            for d in docs
        ]
        self.labels = np.asarray(labels, np.int64)

    def _read_tar(self, path, mode, cutoff):
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        trans = str.maketrans("", "", string.punctuation)
        with tarfile.open(path) as tf:
            names = [n for n in tf.getnames() if pat.match(n)]
            for n in sorted(names):
                text = tf.extractfile(n).read().decode("utf-8", "ignore")
                docs.append(text.lower().translate(trans).split())
                labels.append(0 if "/neg/" in n else 1)
        cnt = Counter(w for d in docs for w in d)
        words = sorted([w for w, c in cnt.items() if c >= cutoff],
                       key=lambda w: (-cnt[w], w))
        self.word_idx = {w: i for i, w in enumerate(words)}
        return docs, labels

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset (reference text/datasets/imikolov.py):
    data_type='NGRAM' yields window_size-grams of word ids; 'SEQ' yields
    (src_seq, trg_seq) shifted sequences with <s>/<e> markers."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=1, num_samples=256):
        assert data_type in ("NGRAM", "SEQ")
        assert mode in ("train", "test")
        if data_type == "NGRAM" and window_size < 2:
            raise ValueError("NGRAM requires window_size >= 2")
        if data_file and os.path.exists(data_file):
            sents = self._read_file(data_file, mode)
        else:
            docs, _ = _synthetic_docs(num_samples,
                                      seed=3 if mode == "train" else 4)
            sents = docs
        cnt = Counter(w for s in sents for w in s)
        words = sorted([w for w, c in cnt.items() if c >= min_word_freq],
                       key=lambda w: (-cnt[w], w))
        self.word_idx = {w: i for i, w in enumerate(words)}
        self.word_idx.setdefault("<unk>", len(self.word_idx))
        self.word_idx.setdefault("<s>", len(self.word_idx))
        self.word_idx.setdefault("<e>", len(self.word_idx))
        unk = self.word_idx["<unk>"]
        self.data = []
        for s in sents:
            ids = [self.word_idx["<s>"]] + [
                self.word_idx.get(w, unk) for w in s] + [self.word_idx["<e>"]]
            if data_type == "NGRAM":
                # reference: ngrams are exactly window_size ids
                # (imikolov.py:153-154)
                for i in range(window_size, len(ids) + 1):
                    self.data.append(
                        np.asarray(ids[i - window_size:i], np.int64))
            else:
                self.data.append((np.asarray(ids[:-1], np.int64),
                                  np.asarray(ids[1:], np.int64)))

    @staticmethod
    def _read_file(path, mode):
        member = f"./simple-examples/data/ptb.{'train' if mode == 'train' else 'valid'}.txt"
        if tarfile.is_tarfile(path):
            with tarfile.open(path) as tf:
                f = tf.extractfile(member)
                text = f.read().decode("utf-8")
        else:
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rt") as f:
                text = f.read()
        return [l.split() for l in text.strip().splitlines() if l.split()]

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """MovieLens-1M ratings (reference text/datasets/movielens.py): samples
    are (user_id, gender, age, job, movie_id, category_ids, title_ids,
    rating) int64/float arrays."""

    MAX_TITLE = 10

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, num_samples=512):
        rng = np.random.RandomState(rand_seed)
        if data_file and os.path.exists(data_file):
            rows = self._read_tar(data_file)
        else:
            rows = self._synthetic(num_samples, rng)
        mask = rng.rand(len(rows)) < test_ratio
        keep = ~mask if mode == "train" else mask
        self.rows = [r for r, k in zip(rows, keep) if k]

    def _synthetic(self, n, rng):
        rows = []
        for _ in range(n):
            rows.append((
                np.asarray([rng.randint(1, 6041)], np.int64),   # user
                np.asarray([rng.randint(0, 2)], np.int64),      # gender
                np.asarray([rng.randint(0, 7)], np.int64),      # age bucket
                np.asarray([rng.randint(0, 21)], np.int64),     # occupation
                np.asarray([rng.randint(1, 3953)], np.int64),   # movie
                np.asarray(rng.randint(0, 19, size=3), np.int64),  # categories
                np.asarray(rng.randint(0, 5000, size=self.MAX_TITLE), np.int64),
                np.asarray([rng.randint(1, 6)], np.float32),    # rating
            ))
        return rows

    def _read_tar(self, path):
        import zipfile

        users, movies, rows = {}, {}, []
        op = zipfile.ZipFile(path) if zipfile.is_zipfile(path) else tarfile.open(path)
        names = op.namelist() if hasattr(op, "namelist") else op.getnames()
        read = (lambda n: op.read(n)) if hasattr(op, "read") else (
            lambda n: op.extractfile(n).read())
        ages = {1: 0, 18: 1, 25: 2, 35: 3, 45: 4, 50: 5, 56: 6}
        cat_idx, title_idx = {}, {}
        for n in names:
            if n.endswith("users.dat"):
                for line in read(n).decode("latin1").splitlines():
                    uid, g, a, job, _ = line.split("::")
                    users[int(uid)] = (int(g == "M"), ages.get(int(a), 0), int(job))
            elif n.endswith("movies.dat"):
                for line in read(n).decode("latin1").splitlines():
                    mid, title, cats = line.split("::")
                    cat_ids = [cat_idx.setdefault(c, len(cat_idx))
                               for c in cats.split("|")]
                    t_ids = [title_idx.setdefault(w, len(title_idx))
                             for w in title.lower().split()[: self.MAX_TITLE]]
                    movies[int(mid)] = (cat_ids, t_ids)
        for n in names:
            if n.endswith("ratings.dat"):
                for line in read(n).decode("latin1").splitlines():
                    uid, mid, r, _ = line.split("::")
                    uid, mid = int(uid), int(mid)
                    if uid not in users or mid not in movies:
                        continue
                    g, a, job = users[uid]
                    cats, title = movies[mid]
                    title = (title + [0] * self.MAX_TITLE)[: self.MAX_TITLE]
                    rows.append((
                        np.asarray([uid], np.int64),
                        np.asarray([g], np.int64),
                        np.asarray([a], np.int64),
                        np.asarray([job], np.int64),
                        np.asarray([mid], np.int64),
                        np.asarray(cats, np.int64),
                        np.asarray(title, np.int64),
                        np.asarray([float(r)], np.float32),
                    ))
        return rows

    def __getitem__(self, idx):
        return self.rows[idx]

    def __len__(self):
        return len(self.rows)


class UCIHousing(Dataset):
    """Boston housing regression (reference text/datasets/uci_housing.py):
    (13 normalized float features, 1 price). Local ``data_file`` is the
    whitespace-separated housing.data text file."""

    FEATURE_DIM = 13

    def __init__(self, data_file=None, mode="train", num_samples=506):
        assert mode in ("train", "test")
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            rng = np.random.RandomState(6)
            feats = rng.rand(num_samples, self.FEATURE_DIM).astype(np.float32)
            w = rng.randn(self.FEATURE_DIM).astype(np.float32)
            price = feats @ w + 0.1 * rng.randn(num_samples).astype(np.float32)
            raw = np.concatenate([feats, price[:, None]], axis=1)
        x, y = raw[:, :-1], raw[:, -1:]
        mn, mx = x.min(0), x.max(0)
        x = (x - x.mean(0)) / np.maximum(mx - mn, 1e-6)
        split = int(len(x) * 0.8)
        if mode == "train":
            self.x, self.y = x[:split], y[:split]
        else:
            self.x, self.y = x[split:], y[split:]

    def __getitem__(self, idx):
        return self.x[idx].astype(np.float32), self.y[idx].astype(np.float32)

    def __len__(self):
        return len(self.x)


class Conll05st(Dataset):
    """CoNLL-2005 semantic role labeling (reference text/datasets/conll05.py):
    samples are (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_id,
    mark, label_ids) — the 5-window context encoding the reference emits."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, num_samples=200):
        rng = np.random.RandomState(8)
        self.word_dict = {w: i for i, w in enumerate(_WORDS + ["<unk>"])}
        self.predicate_dict = {w: i for i, w in enumerate(_POS + _NEG)}
        labels = ["O", "B-A0", "I-A0", "B-A1", "I-A1", "B-V"]
        self.label_dict = {l: i for i, l in enumerate(labels)}
        if word_dict_file and os.path.exists(word_dict_file):
            self.word_dict = self._load_dict(word_dict_file)
        if verb_dict_file and os.path.exists(verb_dict_file):
            self.predicate_dict = self._load_dict(verb_dict_file)
        if target_dict_file and os.path.exists(target_dict_file):
            self.label_dict = self._load_dict(target_dict_file)
        nw = len(self.word_dict)
        self.samples = []
        for _ in range(num_samples):
            ln = int(rng.randint(5, 25))
            words = rng.randint(0, nw, size=ln).astype(np.int64)
            pred_pos = int(rng.randint(0, ln))
            ctx = [np.clip(np.arange(ln) + d, 0, ln - 1) for d in (-2, -1, 0, 1, 2)]
            ctx_ids = [words[c] for c in ctx]
            mark = (np.arange(ln) == pred_pos).astype(np.int64)
            lab = rng.randint(0, len(self.label_dict), size=ln).astype(np.int64)
            pred = np.full((ln,), rng.randint(0, len(self.predicate_dict)),
                           np.int64)
            self.samples.append(tuple(
                [words] + ctx_ids + [pred, mark, lab]))

    @staticmethod
    def _load_dict(path):
        with open(path) as f:
            return {l.strip(): i for i, l in enumerate(f) if l.strip()}

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT14(Dataset):
    """WMT14 en→fr translation (reference text/datasets/wmt14.py): samples
    are (src_ids, trg_ids, trg_ids_next) with <s>/<e>/<unk> as ids 0/1/2."""

    START, END, UNK = 0, 1, 2

    def __init__(self, data_file=None, mode="train", dict_size=1000,
                 num_samples=256):
        assert mode in ("train", "test", "gen", "val")
        self.dict_size = max(int(dict_size), 16)
        rng = np.random.RandomState(10 if mode == "train" else 11)
        self.src_dict = self._mk_dict("src")
        self.trg_dict = self._mk_dict("trg")
        self.samples = []
        if data_file and os.path.exists(data_file):
            pairs = self._read_tar(data_file, mode)
            for src, trg in pairs:
                s = [self._sid(w) for w in src]
                t = [self._tid(w) for w in trg]
                self._append(s, t)
        else:
            for _ in range(num_samples):
                ls = int(rng.randint(3, 20))
                lt = int(rng.randint(3, 20))
                s = rng.randint(3, self.dict_size, size=ls).tolist()
                t = rng.randint(3, self.dict_size, size=lt).tolist()
                self._append(s, t)

    def _mk_dict(self, tag):
        size = self.dict_size
        if tag == "trg" and getattr(self, "trg_dict_size", None):
            size = self.trg_dict_size  # WMT16 per-side dict sizes
        d = {"<s>": self.START, "<e>": self.END, "<unk>": self.UNK}
        for i in range(3, size):
            d[f"{tag}{i}"] = i
        return d

    def _sid(self, w):
        return self.src_dict.get(w, self.UNK)

    def _tid(self, w):
        return self.trg_dict.get(w, self.UNK)

    def _append(self, s, t):
        trg = [self.START] + t
        trg_next = t + [self.END]
        self.samples.append((np.asarray(s, np.int64),
                             np.asarray(trg, np.int64),
                             np.asarray(trg_next, np.int64)))

    @staticmethod
    def _read_tar(path, mode):
        sub = {"train": "train/", "test": "test/", "gen": "gen/",
               "val": "test/"}[mode]
        pairs = []
        with tarfile.open(path) as tf:
            for n in sorted(tf.getnames()):
                if sub in n and not n.endswith("/"):
                    for line in tf.extractfile(n).read().decode(
                            "utf-8", "ignore").splitlines():
                        cols = line.split("\t")
                        if len(cols) >= 2:
                            pairs.append((cols[0].split(), cols[1].split()))
        return pairs

    def get_dict(self, lang="en", reverse=False):
        d = self.src_dict if lang in ("en", "src") else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else dict(d)

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT16(WMT14):
    """WMT16 multimodal en/de (reference text/datasets/wmt16.py). Same sample
    structure as WMT14 with per-side dict sizes and a ``lang`` switch."""

    def __init__(self, data_file=None, mode="train", src_dict_size=1000,
                 trg_dict_size=1000, lang="en", num_samples=256):
        self.lang = lang
        self.trg_dict_size = max(int(trg_dict_size), 16)
        super().__init__(data_file=data_file,
                         mode="train" if mode == "val" else mode,
                         dict_size=src_dict_size, num_samples=num_samples)
