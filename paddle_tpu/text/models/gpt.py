"""GPT family — the flagship LM (driver configs #4/#5: GPT-2 345M sharding,
ERNIE-style pp+tp). API parity with the reference ecosystem's GPT
implementations built on fleet.meta_parallel (mp_layers.py usage pattern);
TPU-first internals: fused QKV projections (one MXU matmul), Pallas/blockwise
flash attention, params carry tp_spec so the fleet engine shards them over
the 'mp'/'sp' mesh axes, and the uniform block stack exposes a functional
form the pipeline engine can scan over stages.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor, apply_op
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.ops.attention import dot_product_attention

__all__ = ["GPTConfig", "GPT", "GPTForCausalLM", "gpt2_small", "gpt2_medium",
           "gpt2_tiny", "gpt_decode_fns"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304  # padded to a multiple of 128 for the MXU
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 1024
    intermediate_size: int = 0  # 0 -> 4*hidden
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    use_flash_attention: bool = True
    # manual LayerNorm VJP scoped to THIS model's forward: +2.2% end-to-end
    # on GPT-2 345M on v5e (it regresses BERT-base 24%, so it is a
    # per-model config rather than a process-wide env default)
    manual_layer_norm: bool = True
    # joint lm_head+CE backward (loss.fused_linear_hard_ce): hands each of
    # the dW/dh dots its own fusable dlogits expression hoping the [N, V]
    # dlogits never materializes. MEASURED OFF: the v5e emitter materializes
    # both expressions instead of operand-fusing them (56.1k vs 56.4k tok/s
    # on the 345M headline), so the default stays on the split
    # linear+_hard_ce path; the knob is kept for rigs whose emitter does
    # operand-fuse dot inputs
    fused_head_ce: bool = False

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_heads
        self.head_dim = h // config.num_heads
        std = config.initializer_range
        # fused qkv: one [h, 3h] matmul feeds the MXU better than 3 separate
        self.qkv = nn.Linear(h, 3 * h, weight_attr=nn.ParamAttr(
            initializer=I.Normal(0.0, std)))
        self.proj = nn.Linear(h, h, weight_attr=nn.ParamAttr(
            initializer=I.Normal(0.0, std / math.sqrt(2 * config.num_layers))))
        # TP: qkv column-parallel (heads split), proj row-parallel
        self.qkv.weight.tp_spec = (None, "mp")
        self.qkv.bias.tp_spec = ("mp",)
        self.proj.weight.tp_spec = ("mp", None)
        self.attn_dropout_p = config.attention_dropout
        self.dropout = nn.Dropout(config.hidden_dropout)
        self.use_flash = config.use_flash_attention

    def forward(self, x, attn_mask=None):
        nh, hd = self.num_heads, self.head_dim
        use_flash = self.use_flash

        def qkv_attend(xr, w, bias):
            from paddle_tpu.amp.auto_cast import maybe_cast_inputs

            # 'linear': the projection must honor the same AMP white/black
            # list entry as every other nn.Linear in the model
            xr, w = maybe_cast_inputs("linear", xr, w)
            b, l, h = xr.shape
            # three separate projections from slices of the fused weight:
            # each of q/k/v is then BORN in the layout its attention einsum
            # wants — a fused [b,l,3h] output forces XLA to materialize
            # relayout copies at the split (measured 6 × 16MB/layer)
            outs = []
            for i in range(3):
                wi = jax.lax.slice_in_dim(w, i * h, (i + 1) * h, axis=1)
                bi = jax.lax.slice_in_dim(bias, i * h, (i + 1) * h, axis=0)
                o = xr @ wi
                outs.append((o + bi.astype(o.dtype)).reshape(b, l, nh, hd))
            q, k, v = outs
            o = dot_product_attention(q, k, v, causal=True,
                                      use_flash=use_flash, layout="blhd")
            return o.reshape(b, l, nh * hd)

        out = apply_op(qkv_attend, x, self.qkv.weight, self.qkv.bias)
        return self.dropout(self.proj(out))


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        std = config.initializer_range
        self.fc = nn.Linear(config.hidden_size, config.intermediate_size,
                            weight_attr=nn.ParamAttr(initializer=I.Normal(0.0, std)))
        self.proj = nn.Linear(config.intermediate_size, config.hidden_size,
                              weight_attr=nn.ParamAttr(
                                  initializer=I.Normal(
                                      0.0, std / math.sqrt(2 * config.num_layers))))
        self.fc.weight.tp_spec = (None, "mp")
        self.fc.bias.tp_spec = ("mp",)
        self.proj.weight.tp_spec = ("mp", None)
        self.dropout = nn.Dropout(config.hidden_dropout)

    def forward(self, x):
        return self.dropout(self.proj(F.gelu(self.fc(x), approximate=True)))


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)

    def forward(self, x, attn_mask=None):
        x = x + self.attn(self.ln_1(x), attn_mask)
        x = x + self.mlp(self.ln_2(x))
        return x


class GPT(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        std = config.initializer_range
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=I.Normal(0.0, std)))
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=I.Normal(0.0, std)))
        # vocab-parallel embedding rows over mp
        self.wte.weight.tp_spec = ("mp", None)
        self.drop = nn.Dropout(config.hidden_dropout)
        self.h = nn.LayerList([GPTBlock(config) for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, attn_mask=None):
        b, l = input_ids.shape
        from paddle_tpu.nn.functional.norm import manual_ln_scope
        from paddle_tpu.tensor import arange

        with manual_ln_scope(self.config.manual_layer_norm):
            pos = arange(l, dtype="int64")
            x = self.wte(input_ids) + self.wpe(pos)
            x = self.drop(x)
            for block in self.h:
                x = block(x, attn_mask)
            return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    """LM head tied to wte (standard GPT-2 weight tying)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPT(config)

    def forward(self, input_ids, labels=None):
        h = self.gpt(input_ids)
        if labels is not None and self.config.fused_head_ce:
            from paddle_tpu.nn.functional.loss import fused_linear_hard_ce

            def head_ce(hr, w, lbl):
                from paddle_tpu.amp.auto_cast import maybe_cast_inputs

                hr2 = hr.reshape(-1, hr.shape[-1])
                hr2, wc = maybe_cast_inputs("linear", hr2, w)
                loss, mask = fused_linear_hard_ce(
                    hr2, wc.T, lbl.reshape(-1).astype(jnp.int32))
                return (jnp.sum(loss)
                        / jnp.maximum(jnp.sum(mask), 1.0)).astype(loss.dtype)

            return apply_op(head_ce, h, self.gpt.wte.weight,
                            labels.detach() if isinstance(labels, Tensor)
                            else labels)
        logits = F.linear(h, _transposed(self.gpt.wte.weight))
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]),
            )
            return loss
        return logits

    def loss_fn(self, logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]), labels.reshape([-1])
        )


def _transposed(w: Tensor) -> Tensor:
    return apply_op(lambda a: a.T, w)


# ---------------------------------------------------------------------------
# Pure functional forms for the pipeline / sp engines
# ---------------------------------------------------------------------------
def gpt_functional_fns(config: GPTConfig, sp_axis=None, mp_axis=None):
    """Pure-jnp (embed_fn, block_fn, head_loss_fn) matching the Layer math
    (dropout-free; use hidden_dropout=0 for exact parity). Used by
    fleet.pipeline_engine (pp over stacked blocks) and the sp ring-attention
    path (sp_axis set → attention rotates K/V around the 'sp' mesh axis).

    ``mp_axis`` set → Megatron-style tensor parallelism INSIDE shard_map
    (the 4D pp×mp×sharding×dp composition the reference builds in
    sharding_optimizer.py:120-138 + tensor_parallel_optimizer.py): the fns
    expect the mp param layout of ``gpt_split_params(..., mp=True)`` —
    head-split qkv [3, h, h/mp], row-parallel proj [h/mp, h], column/row
    mlp, vocab-parallel wte [V/mp, h] — and insert the explicit
    psum/pmax collectives (the reference's _mp_allreduce / vocab-parallel
    cross-entropy) that GSPMD would otherwise derive."""
    nh = config.num_heads
    hd = config.hidden_size // nh
    eps = config.layer_norm_epsilon

    def ln(x, w, b):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + eps) * w + b

    if mp_axis is not None:
        return _gpt_mp_fns(config, ln, sp_axis, mp_axis)

    def embed_fn(p, tokens):
        l = tokens.shape[-1]
        if sp_axis is not None:
            # tokens are sequence-sharded: positions offset by shard index
            off = jax.lax.axis_index(sp_axis) * l
        else:
            off = 0
        pos = off + jnp.arange(l)
        return p["wte"][tokens] + p["wpe"][pos]

    def block_fn(p, h):
        x = ln(h, p["ln_1.weight"], p["ln_1.bias"])
        qkv = x @ p["attn.qkv.weight"] + p["attn.qkv.bias"]
        b, l, _ = qkv.shape
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, l, nh, hd)
        k = k.reshape(b, l, nh, hd)
        v = v.reshape(b, l, nh, hd)
        o = dot_product_attention(q, k, v, causal=True, sp_axis=sp_axis,
                                  use_flash=config.use_flash_attention,
                                  layout="blhd")
        o = o.reshape(b, l, nh * hd)
        h = h + o @ p["attn.proj.weight"] + p["attn.proj.bias"]
        x = ln(h, p["ln_2.weight"], p["ln_2.bias"])
        x = jax.nn.gelu(x @ p["mlp.fc.weight"] + p["mlp.fc.bias"], approximate=True)
        h = h + x @ p["mlp.proj.weight"] + p["mlp.proj.bias"]
        return h

    def head_loss_fn(p, h, labels):
        x = ln(h, p["ln_f.weight"], p["ln_f.bias"])
        logits = x @ p["wte"].T
        logp = jax.nn.log_softmax(logits, axis=-1)
        picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)
        loss = -picked.mean()
        if sp_axis is not None:
            loss = jax.lax.pmean(loss, sp_axis)
        return loss.astype(jnp.float32)

    return embed_fn, block_fn, head_loss_fn


def gpt_decode_fns(config: GPTConfig, kv_dtype: str = "float32"):
    """Pure KV-cached forward for token-level serving
    (``inference.serving.decode``): ONE function covers chunked prefill,
    single-token decode, and speculative verification — they are all
    "advance the cache by a T-token chunk and return the chunk's logits",
    differing only in T.

    Returns ``forward_chunk(params, tokens, q_positions, pages,
    block_tables, kv_lens) -> (logits [B, T, V], pages)`` where
    ``params`` is the flat ``jit.functionalize.get_params`` dict of a
    ``GPTForCausalLM`` and ``pages`` is a ``KVCachePool.pages`` pytree
    (paged layout + scratch-page convention documented in
    inference/serving/kv_cache.py). Each layer writes the chunk's K/V
    into its pages (int8 pools quantize on write via
    ``quant.quantize_kv``), then attends through
    ``ops.attention.paged_attention`` — so the tier policy measures and
    selects the decode attention path exactly like the training tiers.

    Numerics match the eval-mode Layer forward (dropout-free, gelu
    approximate, tied lm_head) up to the attention tier's accumulation
    order — the paged-vs-dense parity test pins the tolerance.
    """
    from paddle_tpu.ops.attention import paged_attention

    nh = config.num_heads
    hd = config.hidden_size // nh
    eps = config.layer_norm_epsilon
    nl = config.num_layers
    max_pos = config.max_position_embeddings
    quantized = kv_dtype == "int8"
    if quantized:
        from paddle_tpu.quant import quantize_kv
    store = jnp.int8 if quantized else jnp.dtype(kv_dtype)

    def ln(x, w, b):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + eps) * w + b

    def forward_chunk(params, tokens, q_positions, pages, block_tables,
                      kv_lens):
        B, T = tokens.shape
        bs = pages["k"].shape[2]
        # scatter targets: token t of row b lands in table slot
        # pos // bs at offset pos % bs; masked-out tokens (padded rows,
        # padded chunk tails — q_position >= kv_len) are redirected to
        # the reserved scratch page 0, so the scatter needs no guard
        valid = q_positions < kv_lens[:, None]
        width = block_tables.shape[1]
        page_idx = jnp.take_along_axis(
            block_tables, jnp.clip(q_positions // bs, 0, width - 1), axis=1)
        page_idx = jnp.where(valid, page_idx, 0)
        slot = q_positions % bs
        pos = jnp.clip(q_positions, 0, max_pos - 1)
        x = params["gpt.wte.weight"][tokens] + params["gpt.wpe.weight"][pos]
        for i in range(nl):
            p = {n: params[f"gpt.h.{i}.{n}"] for n in (
                "ln_1.weight", "ln_1.bias", "attn.qkv.weight",
                "attn.qkv.bias", "attn.proj.weight", "attn.proj.bias",
                "ln_2.weight", "ln_2.bias", "mlp.fc.weight", "mlp.fc.bias",
                "mlp.proj.weight", "mlp.proj.bias")}
            h = ln(x, p["ln_1.weight"], p["ln_1.bias"])
            qkv = h @ p["attn.qkv.weight"] + p["attn.qkv.bias"]
            q3, k3, v3 = jnp.split(qkv, 3, axis=-1)
            q3 = q3.reshape(B, T, nh, hd)
            k3 = k3.reshape(B, T, nh, hd)
            v3 = v3.reshape(B, T, nh, hd)
            if quantized:
                kq, ks = quantize_kv(k3)
                vq, vs = quantize_kv(v3)
                pages["k"] = pages["k"].at[i, page_idx, slot].set(kq)
                pages["v"] = pages["v"].at[i, page_idx, slot].set(vq)
                pages["k_scale"] = \
                    pages["k_scale"].at[i, page_idx, slot].set(ks)
                pages["v_scale"] = \
                    pages["v_scale"].at[i, page_idx, slot].set(vs)
                k_sc, v_sc = pages["k_scale"][i], pages["v_scale"][i]
            else:
                pages["k"] = pages["k"].at[i, page_idx, slot].set(
                    k3.astype(store))
                pages["v"] = pages["v"].at[i, page_idx, slot].set(
                    v3.astype(store))
                k_sc = v_sc = None
            o = paged_attention(q3, pages["k"][i], pages["v"][i],
                                block_tables, q_positions, kv_lens,
                                k_sc, v_sc)
            x = x + o.reshape(B, T, nh * hd) @ p["attn.proj.weight"] \
                + p["attn.proj.bias"]
            h2 = ln(x, p["ln_2.weight"], p["ln_2.bias"])
            h2 = jax.nn.gelu(h2 @ p["mlp.fc.weight"] + p["mlp.fc.bias"],
                             approximate=True)
            x = x + h2 @ p["mlp.proj.weight"] + p["mlp.proj.bias"]
        x = ln(x, params["gpt.ln_f.weight"], params["gpt.ln_f.bias"])
        logits = x @ params["gpt.wte.weight"].T
        return logits, pages

    return forward_chunk


def _gpt_mp_fns(config: GPTConfig, ln, sp_axis, mp_axis):
    """Tensor-parallel functional forms (see gpt_functional_fns)."""
    hd = config.hidden_size // config.num_heads
    V = config.vocab_size

    def embed_fn(p, tokens):
        size = jax.lax.psum(1, mp_axis)
        vloc = p["wte"].shape[0]
        off = jax.lax.axis_index(mp_axis) * vloc
        rel = tokens - off
        ok = (rel >= 0) & (rel < vloc)
        emb = p["wte"][jnp.clip(rel, 0, vloc - 1)] * ok[..., None]
        emb = jax.lax.psum(emb, mp_axis)  # vocab-parallel lookup
        l = tokens.shape[-1]
        seq_off = (jax.lax.axis_index(sp_axis) * l) if sp_axis is not None else 0
        return emb + p["wpe"][seq_off + jnp.arange(l)]

    def block_fn(p, h):
        x = ln(h, p["ln_1.weight"], p["ln_1.bias"])
        # column-parallel qkv: head-split [3, h, h/mp] + local bias
        q = x @ p["attn.qkv.w3"][0] + p["attn.qkv.b3"][0]
        k = x @ p["attn.qkv.w3"][1] + p["attn.qkv.b3"][1]
        v = x @ p["attn.qkv.w3"][2] + p["attn.qkv.b3"][2]
        b, l, hl = q.shape
        q = q.reshape(b, l, hl // hd, hd)
        k = k.reshape(b, l, hl // hd, hd)
        v = v.reshape(b, l, hl // hd, hd)
        o = dot_product_attention(q, k, v, causal=True, sp_axis=sp_axis,
                                  use_flash=config.use_flash_attention,
                                  layout="blhd")
        o = o.reshape(b, l, hl)
        # row-parallel out-projection: partial sums → one psum, bias once
        h = h + jax.lax.psum(o @ p["attn.proj.weight"], mp_axis) \
            + p["attn.proj.bias"]
        x = ln(h, p["ln_2.weight"], p["ln_2.bias"])
        x = jax.nn.gelu(x @ p["mlp.fc.weight"] + p["mlp.fc.bias"],
                        approximate=True)
        h = h + jax.lax.psum(x @ p["mlp.proj.weight"], mp_axis) \
            + p["mlp.proj.bias"]
        return h

    def head_loss_fn(p, h, labels):
        x = ln(h, p["ln_f.weight"], p["ln_f.bias"])
        logits = x @ p["wte"].T                       # [b, l, V/mp] local
        vloc = p["wte"].shape[0]
        off = jax.lax.axis_index(mp_axis) * vloc
        # vocab-parallel cross-entropy (reference
        # parallel_cross_entropy): global max via pmax, global sum-exp and
        # picked logit via psum
        m = jax.lax.pmax(jax.lax.stop_gradient(logits.max(axis=-1)), mp_axis)
        se = jax.lax.psum(
            jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), mp_axis)
        lse = jnp.log(se) + m
        rel = labels - off
        ok = (rel >= 0) & (rel < vloc)
        picked = jnp.take_along_axis(
            logits, jnp.clip(rel, 0, vloc - 1)[..., None], axis=-1)[..., 0]
        picked = jax.lax.psum(picked * ok, mp_axis)
        loss = (lse - picked).mean()
        if sp_axis is not None:
            loss = jax.lax.pmean(loss, sp_axis)
        return loss.astype(jnp.float32)

    return embed_fn, block_fn, head_loss_fn


def gpt_split_params(model: "GPTForCausalLM", tied: bool = False,
                     mp: bool = False):
    """Split a GPTForCausalLM's params into (embed, stacked blocks, head)
    pytrees for the pipeline engine. Block params are stacked over layers.

    ``tied=True`` matches the Layer model's weight tying: the head gets NO
    wte copy — pass ``tie_keys=("wte",)`` to PipelineTrainStep, which
    injects the embedding matrix into the head and syncs its first↔last
    gradients (the reference's Megatron-style tied-embedding allreduce).
    ``tied=False`` unties the LM head (its own trainable copy).

    ``mp=True`` reshapes the attention projections into the
    tensor-parallel layout ``_gpt_mp_fns`` expects: the fused qkv weight
    [h, 3h] becomes head-split "attn.qkv.w3" [L, 3, h, h] (so sharding the
    LAST dim over 'mp' splits each of q/k/v by heads, never mixing them),
    and its bias "attn.qkv.b3" [L, 3, h]. Use with
    ``gpt_mp_param_specs`` as the pipeline engine's param specs."""
    from paddle_tpu.jit.functionalize import get_params

    params = get_params(model)
    n_layers = model.config.num_layers
    embed = {"wte": params["gpt.wte.weight"], "wpe": params["gpt.wpe.weight"]}
    keys = sorted(
        {k.split(".", 3)[3] for k in params if k.startswith("gpt.h.0.")}
    )
    blocks = {
        key: jnp.stack([params[f"gpt.h.{i}.{key}"] for i in range(n_layers)])
        for key in keys
    }
    if mp:
        h = model.config.hidden_size
        w = blocks.pop("attn.qkv.weight")          # [L, h, 3h]
        blocks["attn.qkv.w3"] = w.reshape(
            n_layers, h, 3, h).transpose(0, 2, 1, 3)  # [L, 3, h, h]
        b = blocks.pop("attn.qkv.bias")            # [L, 3h]
        blocks["attn.qkv.b3"] = b.reshape(n_layers, 3, h)
    head = {
        "ln_f.weight": params["gpt.ln_f.weight"],
        "ln_f.bias": params["gpt.ln_f.bias"],
    }
    if not tied:
        # copy keeps donation buffers unique
        head["wte"] = jnp.array(params["gpt.wte.weight"])
    return embed, blocks, head


def gpt_mp_param_specs(pp_axis="pp", mp_axis="mp"):
    """(embed, blocks, head) PartitionSpec trees for the mp param layout
    of ``gpt_split_params(mp=True)`` — column-parallel qkv/fc, row-parallel
    projections, vocab-parallel wte (Megatron placement, matching the
    tp_spec annotations the Layer model carries for the GSPMD engine)."""
    from jax.sharding import PartitionSpec as P

    embed = {"wte": P(mp_axis, None), "wpe": P()}
    blocks = {
        "attn.qkv.w3": P(pp_axis, None, None, mp_axis),
        "attn.qkv.b3": P(pp_axis, None, mp_axis),
        "attn.proj.weight": P(pp_axis, mp_axis, None),
        "attn.proj.bias": P(pp_axis, None),
        "mlp.fc.weight": P(pp_axis, None, mp_axis),
        "mlp.fc.bias": P(pp_axis, mp_axis),
        "mlp.proj.weight": P(pp_axis, mp_axis, None),
        "mlp.proj.bias": P(pp_axis, None),
        "ln_1.weight": P(pp_axis, None),
        "ln_1.bias": P(pp_axis, None),
        "ln_2.weight": P(pp_axis, None),
        "ln_2.bias": P(pp_axis, None),
    }
    head = {"ln_f.weight": P(), "ln_f.bias": P()}
    return embed, blocks, head


def gpt2_tiny(**kw):
    return GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4, num_heads=4,
                     max_position_embeddings=256, hidden_dropout=0.0,
                     attention_dropout=0.0, **kw)


def gpt2_small(**kw):
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt2_medium(**kw):
    """GPT-2 345M (driver config #4)."""
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16, **kw)
