"""BERT / ERNIE family — encoder LMs (driver configs #3 BERT-base fleet DP,
#5 ERNIE-3.0 1.5B pp+tp). API parity with the reference ecosystem's
BERT/ERNIE implementations over paddle.nn (nn/layer/transformer.py
TransformerEncoder usage pattern); TPU-first internals shared with GPT
(text/models/gpt.py): fused QKV in one MXU matmul, flash/blockwise
attention, tp_spec annotations so the fleet engine shards over 'mp'.
ERNIE (this snapshot's architecture) = BERT encoder with its own configs,
so ``ErnieModel``/``ernie_3_0_*`` are config variants of the same stack.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor, apply_op
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.ops.attention import dot_product_attention

__all__ = [
    "BertConfig", "BertModel", "BertForPretraining",
    "BertForSequenceClassification", "bert_base", "bert_large", "bert_tiny",
    "ErnieModel", "ernie_3_0_medium", "ernie_1_5b",
]


@dataclass
class BertConfig:
    vocab_size: int = 30528  # padded to a multiple of 128 for the MXU
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-12
    use_flash_attention: bool = True


class BertSelfAttention(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_heads
        self.head_dim = h // config.num_heads
        std = config.initializer_range
        attr = nn.ParamAttr(initializer=I.Normal(0.0, std))
        # fused QKV: one [h, 3h] matmul on the MXU
        self.qkv = nn.Linear(h, 3 * h, weight_attr=attr)
        self.proj = nn.Linear(h, h, weight_attr=attr)
        # Megatron column/row split over 'mp'
        self.qkv.weight.tp_spec = (None, "mp")
        self.qkv.bias.tp_spec = ("mp",)
        self.proj.weight.tp_spec = ("mp", None)
        self.dropout = nn.Dropout(config.attention_dropout)
        self.use_flash = config.use_flash_attention

    def forward(self, x, attn_bias=None):
        b, l, h = x.shape
        qkv = self.qkv(x)

        def attend(qkv_raw, bias):
            q, k, v = jnp.split(qkv_raw, 3, axis=-1)
            q = q.reshape(b, l, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
            k = k.reshape(b, l, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
            v = v.reshape(b, l, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
            o = dot_product_attention(q, k, v, causal=False, bias=bias,
                                      use_flash=self.use_flash)
            return o.transpose(0, 2, 1, 3).reshape(b, l, h)

        if attn_bias is not None:
            o = apply_op(attend, qkv, attn_bias)
        else:
            o = apply_op(lambda r: attend(r, None), qkv)
        return self.dropout(self.proj(o))


class BertLayer(nn.Layer):
    """Post-LN encoder block (original BERT residual structure)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        std = config.initializer_range
        attr = nn.ParamAttr(initializer=I.Normal(0.0, std))
        self.attn = BertSelfAttention(config)
        self.ln1 = nn.LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.fc1 = nn.Linear(h, config.intermediate_size, weight_attr=attr)
        self.fc2 = nn.Linear(config.intermediate_size, h, weight_attr=attr)
        self.fc1.weight.tp_spec = (None, "mp")
        self.fc1.bias.tp_spec = ("mp",)
        self.fc2.weight.tp_spec = ("mp", None)
        self.ln2 = nn.LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.dropout = nn.Dropout(config.hidden_dropout)

    def forward(self, x, attn_bias=None):
        x = self.ln1(x + self.attn(x, attn_bias))
        y = self.fc2(F.gelu(self.fc1(x), approximate=True))
        return self.ln2(x + self.dropout(y))


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        std = config.initializer_range
        attr = nn.ParamAttr(initializer=I.Normal(0.0, std))
        self.word = nn.Embedding(config.vocab_size, config.hidden_size,
                                 weight_attr=attr)
        self.word.weight.tp_spec = ("mp", None)  # vocab-parallel rows
        self.position = nn.Embedding(config.max_position_embeddings,
                                     config.hidden_size, weight_attr=attr)
        self.token_type = nn.Embedding(config.type_vocab_size,
                                       config.hidden_size, weight_attr=attr)
        self.ln = nn.LayerNorm(config.hidden_size,
                               epsilon=config.layer_norm_epsilon)
        self.dropout = nn.Dropout(config.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None):
        from paddle_tpu.tensor import arange, zeros_like

        b, l = input_ids.shape
        pos = arange(l, dtype="int64")
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        x = self.word(input_ids) + self.position(pos) + \
            self.token_type(token_type_ids)
        return self.dropout(self.ln(x))


class BertModel(nn.Layer):
    """Reference API shape: returns (sequence_output, pooled_output)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList(
            [BertLayer(config) for _ in range(config.num_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        attn_bias = None
        if attention_mask is not None:
            # [b, l] 1/0 mask → additive bias broadcastable to [b, h, lq, lk]
            attn_bias = apply_op(
                lambda m: (1.0 - m.astype(jnp.float32))[:, None, None, :] * -1e9,
                attention_mask,
            )
        x = self.embeddings(input_ids, token_type_ids)
        for layer in self.encoder:
            x = layer(x, attn_bias)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (BertPretrainingCriterion parity)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.config = config
        h = config.hidden_size
        self.mlm_transform = nn.Linear(h, h)
        self.mlm_ln = nn.LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.nsp = nn.Linear(h, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        x = self.mlm_ln(F.gelu(self.mlm_transform(seq), approximate=True))
        # decoder tied to word embeddings
        logits = F.linear(x, apply_op(lambda w: w.T, self.bert.embeddings.word.weight))
        nsp_logits = self.nsp(pooled)
        return logits, nsp_logits

    def loss_fn(self, outputs, mlm_labels, nsp_labels=None):
        """mlm_labels: [b, l] with -100 = unmasked (ignored)."""
        logits, nsp_logits = outputs

        def masked_ce(lg, lab):
            v = lg.shape[-1]
            lg2 = lg.reshape(-1, v)
            lab2 = lab.reshape(-1)
            valid = lab2 >= 0
            lab_safe = jnp.where(valid, lab2, 0)
            logp = jax.nn.log_softmax(lg2, axis=-1)
            picked = jnp.take_along_axis(logp, lab_safe[:, None], axis=-1)[:, 0]
            return -(picked * valid).sum() / jnp.maximum(valid.sum(), 1)

        loss = apply_op(masked_ce, logits, mlm_labels)
        if nsp_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits, nsp_labels)
        return loss


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))


def bert_tiny(**kw):
    return BertConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                      num_heads=2, intermediate_size=512,
                      max_position_embeddings=128, hidden_dropout=0.0,
                      attention_dropout=0.0, **kw)


def bert_base(**kw):
    """BERT-base (driver config #3: fleet DP pretrain)."""
    return BertConfig(**kw)


def bert_large(**kw):
    return BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                      intermediate_size=4096, **kw)


# --- ERNIE: same encoder architecture, its own configs -----------------------
class ErnieModel(BertModel):
    """ERNIE (this reference snapshot's ERNIE is a BERT-architecture encoder
    with knowledge-masking pretraining; the model graph is identical)."""


def ernie_3_0_medium(**kw):
    return BertConfig(vocab_size=40064, hidden_size=768, num_layers=6,
                      num_heads=12, intermediate_size=3072, **kw)


def ernie_1_5b(**kw):
    """ERNIE-3.0 1.5B-class config (driver config #5: pp+tp on v5p-32)."""
    return BertConfig(vocab_size=40064, hidden_size=2048, num_layers=24,
                      num_heads=16, intermediate_size=8192,
                      max_position_embeddings=2048, **kw)
