from .bert import (  # noqa: F401
    BertConfig,
    BertForPretraining,
    BertForSequenceClassification,
    BertModel,
    ErnieModel,
    bert_base,
    bert_large,
    bert_tiny,
    ernie_1_5b,
    ernie_3_0_medium,
)
from .gpt import (  # noqa: F401
    GPT,
    GPTConfig,
    GPTForCausalLM,
    gpt2_medium,
    gpt2_small,
    gpt2_tiny,
)
