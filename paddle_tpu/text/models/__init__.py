from .gpt import (  # noqa: F401
    GPT,
    GPTConfig,
    GPTForCausalLM,
    gpt2_medium,
    gpt2_small,
    gpt2_tiny,
)
