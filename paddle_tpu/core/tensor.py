"""Eager Tensor facade and define-by-run autograd over JAX.

Capability parity with the reference's imperative engine — VarBase + Tracer +
BasicEngine (/root/reference/paddle/fluid/imperative/tracer.cc:133,
/root/reference/paddle/fluid/imperative/basic_engine.cc:305) — redesigned for
XLA: instead of a per-op kernel dispatch with hand-written grad ops, every
eager op runs through ``jax.vjp``, which both executes the forward on-device
and captures a pullback closure. ``Tensor.backward()`` walks the resulting
DAG of pullbacks in reverse topological order.

The DAG is held by strong references from output tensors to their producer
``Node`` (and from nodes to input tensors), so Python GC frees the graph as
soon as the forward outputs go out of scope — no global tape, no leak in
inference loops.

For hot training loops, the same layer/op code can be staged: tracing runs
this module's ops with JAX tracers inside ``jax.jit`` (see paddle_tpu.jit),
where autograd recording is disabled and ``jax.grad`` differentiates the
whole step — that is the path that reaches MXU-peak performance.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtype_mod
from . import place as place_mod
from . import rng as rng_mod
from .enforce import InvalidArgumentError, enforce
from .flags import flag_value

__all__ = [
    "Tensor",
    "Parameter",
    "to_tensor",
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
    "apply_op",
    "wrap_raw",
]


# ---------------------------------------------------------------------------
# grad mode
# ---------------------------------------------------------------------------
class _GradMode(threading.local):
    def __init__(self):
        self.enabled = True


_grad_mode = _GradMode()


def is_grad_enabled() -> bool:
    return _grad_mode.enabled


def set_grad_enabled(mode: bool):
    class _Ctx:
        def __init__(self, mode):
            self._mode = bool(mode)
            self._prev = _grad_mode.enabled
            _grad_mode.enabled = self._mode

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            _grad_mode.enabled = self._prev
            return False

    return _Ctx(mode)


@contextlib.contextmanager
def no_grad():
    prev = _grad_mode.enabled
    _grad_mode.enabled = False
    try:
        yield
    finally:
        _grad_mode.enabled = prev


def no_grad_decorator(fn):
    def wrapper(*a, **k):
        with no_grad():
            return fn(*a, **k)

    wrapper.__name__ = getattr(fn, "__name__", "no_grad_fn")
    return wrapper


@contextlib.contextmanager
def enable_grad():
    prev = _grad_mode.enabled
    _grad_mode.enabled = True
    try:
        yield
    finally:
        _grad_mode.enabled = prev


# ---------------------------------------------------------------------------
# autograd DAG node
# ---------------------------------------------------------------------------
class Node:
    """One recorded eager op: inputs, pullback, and output metadata.

    ``fwd_fn`` (when present) is the pure forward closure over the node's
    differentiable inputs — kept so a ``create_graph=True`` backward can
    RE-LINEARIZE the op at its original inputs (vjp-of-vjp), capturing the
    second-order dependence of the gradient on the inputs that the stored
    first-order ``vjp_fn``'s residuals hide (the eager equivalent of the
    reference's PartialGradEngine double-grad,
    imperative/partial_grad_engine.cc)."""

    __slots__ = ("inputs", "vjp_fn", "out_avals", "out_grads", "n_outs",
                 "name", "fwd_fn", "tuple_out")

    def __init__(self, inputs, vjp_fn, out_avals, name="", fwd_fn=None,
                 tuple_out=False):
        self.inputs: List[Tensor] = inputs
        self.vjp_fn = vjp_fn
        self.out_avals = out_avals  # list of (shape, dtype)
        self.out_grads: Optional[List[Any]] = None
        self.n_outs = len(out_avals)
        self.name = name
        self.fwd_fn = fwd_fn
        # True when the recorded fn returned a TUPLE (multi_out): the vjp's
        # cotangent must then be a tuple even for a single output
        self.tuple_out = tuple_out

    def seed_zero_grads(self):
        if self.out_grads is None:
            self.out_grads = [None] * self.n_outs

    def accumulate(self, idx, g):
        self.seed_zero_grads()
        if self.out_grads[idx] is None:
            self.out_grads[idx] = g
        else:
            self.out_grads[idx] = self.out_grads[idx] + g


# ---------------------------------------------------------------------------
# Tensor
# ---------------------------------------------------------------------------
class Tensor:
    """Imperative tensor wrapping a ``jax.Array`` (or a JAX tracer when the
    surrounding code is being staged by ``paddle_tpu.jit``)."""

    # populated by paddle_tpu.tensor via _register_tensor_method
    __slots__ = (
        "_value",
        "_node",
        "_idx",
        "stop_gradient",
        "grad",
        "name",
        "persistable",
        "_retain_grads",
        "_grad_hooks",
        "__weakref__",
    )

    _next_id = [0]

    def __init__(self, value, stop_gradient=True, name=None):
        self._value = value
        self._node: Optional[Node] = None
        self._idx = 0
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self.persistable = False
        self._retain_grads = False
        self._grad_hooks: List[Callable] = []
        if name is None:
            Tensor._next_id[0] += 1
            name = f"generated_tensor_{Tensor._next_id[0]}"
        self.name = name

    # -- basic properties ----------------------------------------------------
    @property
    def shape(self) -> list:
        return list(self._value.shape)

    @property
    def ndim(self) -> int:
        return self._value.ndim

    ndimension = ndim

    @property
    def size(self) -> int:
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def place(self):
        try:
            dev = self._value.devices() if hasattr(self._value, "devices") else None
            if dev:
                d = next(iter(dev))
                return (
                    place_mod.TPUPlace(d.id)
                    if d.platform == "tpu"
                    else place_mod.CPUPlace()
                )
        except Exception:
            pass
        return place_mod._default_place()

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    @property
    def T(self):
        from .. import tensor as T

        return T.transpose(self, list(range(self.ndim))[::-1])

    def numel(self) -> int:
        return self.size

    def dim(self) -> int:
        return self.ndim

    # -- conversion ----------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        enforce(
            self.size == 1,
            "The truth value of a Tensor with more than one element is ambiguous",
        )
        return bool(self.numpy().item())

    def __len__(self):
        enforce(self.ndim > 0, "len() of a 0-d tensor")
        return self.shape[0]

    def __repr__(self):
        if _is_tracer(self._value):
            val = self._value
        else:
            from ..tensor.to_string import array_repr

            val = array_repr(self._value)  # honors set_printoptions
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"stop_gradient={self.stop_gradient},\n       {val})"
        )

    # -- autograd ------------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        backward(self, grad_tensor, retain_graph)

    def retain_grads(self):
        self._retain_grads = True

    def register_hook(self, hook: Callable):
        """Register a gradient hook (parity imperative/hooks.h). The hook
        receives the grad Tensor and may return a replacement."""
        self._grad_hooks.append(hook)

        class _Remover:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)

        return _Remover(self._grad_hooks, hook)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        return apply_op(lambda x: x + jnp.zeros((), x.dtype), self)

    # -- dtype / device ------------------------------------------------------
    def astype(self, dtype) -> "Tensor":
        d = dtype_mod.convert_dtype(dtype)
        return apply_op(lambda x: x.astype(d), self)

    def cast(self, dtype):
        return self.astype(dtype)

    def cpu(self):
        return Tensor(
            jax.device_put(self._value, jax.devices("cpu")[0]),
            stop_gradient=self.stop_gradient,
        )

    def tpu(self, device_id=0):
        return Tensor(
            jax.device_put(self._value, place_mod.TPUPlace(device_id).jax_device()),
            stop_gradient=self.stop_gradient,
        )

    cuda = tpu

    def pin_memory(self):
        return self.cpu()

    def to(self, *args, **kwargs):
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a.split(":")[0] in ("cpu", "tpu", "gpu"):
                out = out.cpu() if a.startswith("cpu") else out.tpu()
            elif isinstance(a, place_mod.Place):
                out = Tensor(
                    jax.device_put(out._value, a.jax_device()),
                    stop_gradient=out.stop_gradient,
                )
            else:
                out = out.astype(a)
        return out

    # -- in-place value assignment (imperative semantics) --------------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        arr = jnp.asarray(value, dtype=self._value.dtype)
        enforce(
            tuple(arr.shape) == tuple(self._value.shape),
            f"set_value shape mismatch {arr.shape} vs {self._value.shape}",
        )
        self._value = arr

    def copy_(self, other, *a):
        self.set_value(other)
        return self

    def fill_(self, v):
        self._value = jnp.full_like(self._value, v)
        return self

    def zero_(self):
        return self.fill_(0)

    def _rebind(self, new: "Tensor"):
        """Point this python object at a new graph value (setitem etc.)."""
        self._value = new._value
        self._node = new._node
        self._idx = new._idx
        self.stop_gradient = new.stop_gradient

    # -- indexing ------------------------------------------------------------
    def __getitem__(self, idx):
        idx = _unwrap_index(idx)
        return apply_op(lambda x: x[idx], self)

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        if isinstance(value, Tensor):
            new = apply_op(
                lambda x, v: x.at[idx].set(v.astype(x.dtype)), self, value
            )
        else:
            new = apply_op(lambda x: x.at[idx].set(value), self)
        self._rebind(new)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- python numeric protocol (rich surface attached by paddle_tpu.tensor)
    def __neg__(self):
        return apply_op(jnp.negative, self)

    def __abs__(self):
        return apply_op(jnp.abs, self)

    def __add__(self, o):
        return _binop(jnp.add, self, o)

    def __radd__(self, o):
        return _binop(jnp.add, o, self)

    def __sub__(self, o):
        return _binop(jnp.subtract, self, o)

    def __rsub__(self, o):
        return _binop(jnp.subtract, o, self)

    def __mul__(self, o):
        return _binop(jnp.multiply, self, o)

    def __rmul__(self, o):
        return _binop(jnp.multiply, o, self)

    def __truediv__(self, o):
        return _binop(jnp.true_divide, self, o)

    def __rtruediv__(self, o):
        return _binop(jnp.true_divide, o, self)

    def __floordiv__(self, o):
        return _binop(jnp.floor_divide, self, o)

    def __rfloordiv__(self, o):
        return _binop(jnp.floor_divide, o, self)

    def __mod__(self, o):
        return _binop(jnp.mod, self, o)

    def __rmod__(self, o):
        return _binop(jnp.mod, o, self)

    def __pow__(self, o):
        return _binop(jnp.power, self, o)

    def __rpow__(self, o):
        return _binop(jnp.power, o, self)

    def __matmul__(self, o):
        return _binop(jnp.matmul, self, o)

    def __rmatmul__(self, o):
        return _binop(jnp.matmul, o, self)

    def __eq__(self, o):
        return _binop(jnp.equal, self, o)

    def __ne__(self, o):
        return _binop(jnp.not_equal, self, o)

    def __lt__(self, o):
        return _binop(jnp.less, self, o)

    def __le__(self, o):
        return _binop(jnp.less_equal, self, o)

    def __gt__(self, o):
        return _binop(jnp.greater, self, o)

    def __ge__(self, o):
        return _binop(jnp.greater_equal, self, o)

    def __invert__(self):
        return apply_op(jnp.logical_not, self)

    def __and__(self, o):
        return _binop(_and_like, self, o)

    def __or__(self, o):
        return _binop(_or_like, self, o)

    def __xor__(self, o):
        return _binop(_xor_like, self, o)

    def __hash__(self):
        return id(self)


def _and_like(a, b):
    if a.dtype == np.bool_:
        return jnp.logical_and(a, b)
    return jnp.bitwise_and(a, b)


def _or_like(a, b):
    if a.dtype == np.bool_:
        return jnp.logical_or(a, b)
    return jnp.bitwise_or(a, b)


def _xor_like(a, b):
    if a.dtype == np.bool_:
        return jnp.logical_xor(a, b)
    return jnp.bitwise_xor(a, b)


class Parameter(Tensor):
    """Trainable tensor — parity with ParamBase
    (/root/reference/python/paddle/fluid/framework.py:5727)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip",
                 "is_distributed", "tp_spec")

    def __init__(self, value, trainable=True, name=None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        # tensor-parallel PartitionSpec axes, e.g. (None, "mp") — consumed by
        # the fleet engine's sharding propagation
        self.tp_spec = None
        self.persistable = True

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


# ---------------------------------------------------------------------------
# op application: the eager hot path
# ---------------------------------------------------------------------------
def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return [_unwrap_index(i) for i in idx]
    if isinstance(idx, slice):
        return slice(
            _unwrap_index(idx.start), _unwrap_index(idx.stop), _unwrap_index(idx.step)
        )
    return idx


def wrap_raw(value, stop_gradient=True) -> Tensor:
    return Tensor(value, stop_gradient=stop_gradient)


def _differentiable(x) -> bool:
    return isinstance(x, Tensor) and not x.stop_gradient


def _float_like(aval_dtype) -> bool:
    return jnp.issubdtype(aval_dtype, jnp.floating) or jnp.issubdtype(
        aval_dtype, jnp.complexfloating
    )


# Hook installed by paddle_tpu.static.program_guard: when set, every op is
# also appended to the active Program's SSA trace (the ProgramDesc-equivalent).
_op_recorder: Optional[Callable] = None


def apply_op(fn: Callable, *args, multi_out: bool = False, op_name: str = ""):
    """Run ``fn`` over raw arrays; record a pullback node when needed.

    ``args`` may mix Tensors and raw values; only floating-point Tensor inputs
    with ``stop_gradient=False`` participate in differentiation.
    """
    raws = [a._value if isinstance(a, Tensor) else a for a in args]
    record = _grad_mode.enabled and any(
        _differentiable(a) and _float_like(a._value.dtype) for a in args
    )
    if not record:
        out = fn(*raws)
        if flag_value("check_nan_inf"):
            _check_nan_inf(out, op_name or getattr(fn, "__name__", "op"))
        if multi_out:
            outs = tuple(wrap_raw(o) for o in out)
        else:
            outs = wrap_raw(out)
        if _op_recorder is not None:
            _op_recorder(
                fn, args, outs if multi_out else (outs,), multi_out,
                op_name or getattr(fn, "__name__", "op"),
            )
        return outs

    diff_pos = [
        i
        for i, a in enumerate(args)
        if _differentiable(a) and _float_like(a._value.dtype)
    ]
    diff_raws = [raws[i] for i in diff_pos]

    def f(*diff):
        full = list(raws)
        for p, v in zip(diff_pos, diff):
            full[p] = v
        return fn(*full)

    out, vjp_fn = jax.vjp(f, *diff_raws)
    if flag_value("check_nan_inf"):
        _check_nan_inf(out, op_name or getattr(fn, "__name__", "op"))
    outs = out if multi_out else (out,)
    node = Node(
        [args[i] for i in diff_pos],
        vjp_fn,
        [(o.shape, o.dtype) for o in outs],
        name=op_name or getattr(fn, "__name__", "op"),
        fwd_fn=f,
        tuple_out=multi_out,
    )
    wrapped = []
    for i, o in enumerate(outs):
        t = Tensor(o, stop_gradient=not _float_like(o.dtype))
        if not t.stop_gradient:
            t._node = node
            t._idx = i
        wrapped.append(t)
    if _op_recorder is not None:
        _op_recorder(
            fn, args, tuple(wrapped), multi_out,
            op_name or getattr(fn, "__name__", "op"),
        )
    return tuple(wrapped) if multi_out else wrapped[0]


def _binop(fn, a, b):
    return apply_op(fn, *_promote_pair(a, b))


def _promote_pair(a, b):
    """Align python scalars to the tensor operand's dtype family so that
    e.g. float_tensor + 2 stays in the tensor dtype (paddle semantics),
    instead of numpy-style promotion to a wider type."""
    if isinstance(a, Tensor) and not isinstance(b, Tensor):
        if isinstance(b, (bool, int, float)) and _float_like(a._value.dtype):
            b = jnp.asarray(b, dtype=a._value.dtype)
        elif isinstance(b, (bool, int)) and jnp.issubdtype(
            a._value.dtype, jnp.integer
        ):
            b = jnp.asarray(b, dtype=a._value.dtype)
    elif isinstance(b, Tensor) and not isinstance(a, Tensor):
        if isinstance(a, (bool, int, float)) and _float_like(b._value.dtype):
            a = jnp.asarray(a, dtype=b._value.dtype)
        elif isinstance(a, (bool, int)) and jnp.issubdtype(
            b._value.dtype, jnp.integer
        ):
            a = jnp.asarray(a, dtype=b._value.dtype)
    return a, b


def _check_nan_inf(out, name):
    """FLAGS_check_nan_inf runtime sanitizer — parity with the reference's
    nan_inf_utils (framework/details/nan_inf_utils_detail.cc)."""
    leaves = jax.tree_util.tree_leaves(out)
    for leaf in leaves:
        if hasattr(leaf, "dtype") and _float_like(leaf.dtype) and not _is_tracer(leaf):
            if not bool(jnp.all(jnp.isfinite(leaf))):
                raise FloatingPointError(f"NaN or Inf found in output of op {name!r}")


# ---------------------------------------------------------------------------
# backward engine
# ---------------------------------------------------------------------------
def _topo_nodes(root: Node) -> List[Node]:
    """Iterative DFS postorder => reverse is a valid reverse-topo sweep."""
    seen = set()
    order: List[Node] = []
    stack: List[tuple] = [(root, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for inp in node.inputs:
            if inp._node is not None and id(inp._node) not in seen:
                stack.append((inp._node, False))
    return order


def backward(tensor: Tensor, grad_tensor=None, retain_graph=False,
             create_graph=False):
    """Reverse-mode sweep — parity with BasicEngine::Execute
    (imperative/basic_engine.cc:305). ``create_graph=True`` runs the
    DIFFERENTIABLE sweep (see ``_backward_create_graph``)."""
    if create_graph:
        return _backward_create_graph(tensor, grad_tensor)
    if grad_tensor is None:
        seed = jnp.ones(tensor._value.shape, tensor._value.dtype)
    else:
        seed = grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    if tensor._node is None:
        if not tensor.stop_gradient:
            _accum_leaf(tensor, seed)
        return

    order = _topo_nodes(tensor._node)
    tensor._node.seed_zero_grads()
    tensor._node.accumulate(tensor._idx, seed)

    for node in reversed(order):
        if node.out_grads is None or all(g is None for g in node.out_grads):
            node.out_grads = None
            continue
        cotangents = [
            g if g is not None else jnp.zeros(shape, dtype)
            for g, (shape, dtype) in zip(node.out_grads, node.out_avals)
        ]
        ct = (tuple(cotangents) if (node.n_outs > 1 or node.tuple_out)
              else cotangents[0])
        if node.vjp_fn is None:
            raise RuntimeError(
                "trying to backward through a graph that has already been "
                "freed; call backward(retain_graph=True) if you need to "
                "backward through it a second time"
            )
        in_grads = node.vjp_fn(ct)
        for inp, g in zip(node.inputs, in_grads):
            if g is None or inp.stop_gradient:
                continue
            if getattr(g, "dtype", None) is not None and g.dtype == jax.dtypes.float0:
                continue
            for hook in inp._grad_hooks:
                from .selected_rows import RowSparseGrad

                res = hook(g if isinstance(g, RowSparseGrad) else wrap_raw(g))
                if res is not None:
                    g = res._value if isinstance(res, Tensor) else res
            if inp._node is not None:
                inp._node.accumulate(inp._idx, g)
                if inp._retain_grads:
                    _accum_leaf(inp, g)
            else:
                _accum_leaf(inp, g)
        node.out_grads = None
        if not retain_graph:
            node.vjp_fn = None  # free residuals eagerly

    if not retain_graph:
        # Drop graph edges so memory is reclaimed; mirrors the reference's
        # retain_graph=False default behavior. fwd_fn goes too: its closure
        # pins the captured input arrays, and a later create_graph sweep
        # must hit the freed-graph guard, not silently see empty inputs.
        for node in order:
            node.inputs = []
            node.fwd_fn = None


def _backward_create_graph(tensor: Tensor, grad_tensor=None):
    """Differentiable reverse sweep: every backward computation is itself
    recorded on the tape, so the produced ``.grad`` Tensors can be
    differentiated again (``paddle.grad(..., create_graph=True)``, WGAN-GP
    style gradient penalties). Parity:
    /root/reference/paddle/fluid/imperative/partial_grad_engine.cc.

    Each node is RE-LINEARIZED at its original inputs through ``apply_op``
    (grads = vjp(fwd_fn, *xs)(ct) as one recorded op): the resulting tape
    op depends on BOTH the cotangent and the original inputs, so
    second-order terms survive. Ops recorded without a forward closure
    (PyLayer custom backwards) are rejected explicitly. The graph is always
    retained (the second backward needs it)."""
    if grad_tensor is None:
        seed = wrap_raw(jnp.ones(tensor._value.shape, tensor._value.dtype))
    elif isinstance(grad_tensor, Tensor):
        seed = grad_tensor
    else:
        seed = wrap_raw(jnp.asarray(grad_tensor))

    if tensor._node is None:
        if not tensor.stop_gradient:
            _accum_leaf(tensor, seed)
        return

    order = _topo_nodes(tensor._node)
    tensor._node.seed_zero_grads()
    tensor._node.accumulate(tensor._idx, seed)

    for node in reversed(order):
        if node.out_grads is None or all(g is None for g in node.out_grads):
            node.out_grads = None
            continue
        if node.fwd_fn is None:
            if not node.inputs and node.vjp_fn is None:
                raise RuntimeError(
                    "trying to backward through a graph that has already "
                    "been freed; call backward(retain_graph=True) if you "
                    "need to backward through it a second time")
            raise NotImplementedError(
                f"create_graph=True cannot differentiate through op "
                f"{node.name!r}: it was recorded without a replayable "
                "forward (PyLayer custom backward). Express it with "
                "differentiable tensor ops to use double-grad.")
        cotangents = [
            g if g is not None else wrap_raw(jnp.zeros(shape, dtype))
            for g, (shape, dtype) in zip(node.out_grads, node.out_avals)
        ]
        n_in = len(node.inputs)
        fwd_fn, n_outs = node.fwd_fn, node.n_outs

        tup = node.tuple_out

        def replay(*xs_and_cts, _fwd=fwd_fn, _n_in=n_in, _n_outs=n_outs,
                   _tup=tup):
            xs, cts = xs_and_cts[:_n_in], xs_and_cts[_n_in:]
            _, vjp = jax.vjp(_fwd, *xs)
            ct = tuple(cts) if (_n_outs > 1 or _tup) else cts[0]
            return vjp(ct)  # tuple of len(xs) grads

        in_grads = apply_op(replay, *node.inputs, *cotangents,
                            multi_out=True, op_name=f"grad({node.name})")
        for inp, g in zip(node.inputs, in_grads):
            if inp.stop_gradient:
                continue
            for hook in inp._grad_hooks:
                res = hook(g)
                if res is not None:
                    g = res
            if inp._node is not None:
                inp._node.accumulate(inp._idx, g)
                if inp._retain_grads:
                    _accum_leaf(inp, g)
            else:
                _accum_leaf(inp, g)
        node.out_grads = None


def _accum_leaf(t: Tensor, g):
    from .selected_rows import RowSparseGrad

    if isinstance(g, Tensor):  # differentiable sweep: keep the tape alive
        t.grad = g if t.grad is None else t.grad + g
        return
    if isinstance(g, RowSparseGrad):
        # SelectedRows-equivalent: keep the sparse form on the leaf; the
        # optimizer's sparse path consumes it. sparse+sparse concatenates,
        # sparse+dense densifies (to a Tensor).
        acc = g + t.grad if t.grad is not None else g
        t.grad = acc if isinstance(acc, RowSparseGrad) else wrap_raw(acc)
        return
    if t.grad is None:
        t.grad = wrap_raw(g)
    elif isinstance(t.grad, RowSparseGrad):
        t.grad = wrap_raw(t.grad.to_dense() + g)
    else:
        t.grad = wrap_raw(t.grad._value + g)


# ---------------------------------------------------------------------------
# to_tensor
# ---------------------------------------------------------------------------
def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """Parity with paddle.to_tensor: python ints -> int64, floats -> default
    float dtype; numpy arrays keep their dtype unless ``dtype`` is given."""
    d = dtype_mod.convert_dtype(dtype)
    if isinstance(data, Tensor):
        arr = data._value
        if d is not None and arr.dtype != d:
            arr = arr.astype(d)
        out = Tensor(arr, stop_gradient=stop_gradient)
        return out
    if isinstance(data, (bool, int, float, complex)) or (
        isinstance(data, (list, tuple)) and _all_py_scalars(data)
    ):
        npd = np.asarray(data)
        if d is None:
            if npd.dtype == np.float64:
                d = dtype_mod.get_default_dtype()
            elif npd.dtype == np.int64:
                d = np.dtype(np.int64)
        npd = npd.astype(d) if d is not None else npd
        data = npd
    elif isinstance(data, np.ndarray):
        if d is not None and data.dtype != d:
            data = data.astype(d)
    dev = place_mod._place_from_any(place).jax_device() if place is not None else None
    arr = jnp.asarray(data, dtype=d)
    if dev is not None:
        arr = jax.device_put(arr, dev)
    return Tensor(arr, stop_gradient=stop_gradient)


def _all_py_scalars(seq) -> bool:
    for x in seq:
        if isinstance(x, (list, tuple)):
            if not _all_py_scalars(x):
                return False
        elif not isinstance(x, (bool, int, float, complex)):
            return False
    return True
