"""Typed error model.

Mirrors the reference's PADDLE_ENFORCE macros + error_codes.proto
(/root/reference/paddle/fluid/platform/enforce.h:415-445,
/root/reference/paddle/fluid/platform/error_codes.proto): every error carries
a typed category so callers/tests can assert on the failure class.
"""
from __future__ import annotations


class EnforceNotMet(RuntimeError):
    """Base error, parity with platform::EnforceNotMet."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class FatalError(EnforceNotMet):
    pass


class ExternalError(EnforceNotMet):
    pass


def enforce(cond, message: str, error_cls=InvalidArgumentError):
    if not cond:
        raise error_cls(message)


def enforce_eq(a, b, message: str = "", error_cls=InvalidArgumentError):
    if a != b:
        raise error_cls(f"expected {a!r} == {b!r}. {message}")


def enforce_gt(a, b, message: str = "", error_cls=InvalidArgumentError):
    if not a > b:
        raise error_cls(f"expected {a!r} > {b!r}. {message}")


def enforce_ge(a, b, message: str = "", error_cls=InvalidArgumentError):
    if not a >= b:
        raise error_cls(f"expected {a!r} >= {b!r}. {message}")


def enforce_not_none(x, message: str = "", error_cls=NotFoundError):
    if x is None:
        raise error_cls(f"expected a value, got None. {message}")
    return x
