"""Core runtime: tensor, autograd, dtype, place, flags, rng, errors."""
import jax as _jax

# int64/float64 must exist for API parity with the reference (python ints
# create int64 tensors, framework.py to_tensor semantics). All internal ops
# pass explicit dtypes so the x64 default does not leak into compute.
_jax.config.update("jax_enable_x64", True)

from . import dtype, enforce, flags, monitor, place, rng, tensor  # noqa: E402,F401
from .dtype import (  # noqa: E402,F401
    bfloat16,
    bool_,
    complex64,
    complex128,
    convert_dtype,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .enforce import *  # noqa: E402,F401,F403
from .flags import get_flags, set_flags  # noqa: E402,F401
from .place import (  # noqa: E402,F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
)
from .rng import get_rng_state_tracker, seed  # noqa: E402,F401
from .tensor import (  # noqa: E402,F401
    Parameter,
    Tensor,
    apply_op,
    backward,
    enable_grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
    to_tensor,
    wrap_raw,
)
