"""Device/Place model.

The reference's Place is a typed device identity used as the kernel-dispatch
key (/root/reference/paddle/fluid/platform/place.h:128). On TPU, XLA owns
kernel dispatch, so Place here is a thin identity that maps onto a
``jax.Device`` and is used for explicit data placement (``to_tensor(place=)``,
``Tensor.cuda()``-style moves become device_put) and for API parity.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Base device identity."""

    _kind = "unknown"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self._kind == other._kind
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self._kind, self._device_id))

    def __repr__(self):
        return f"Place({self._kind}:{self._device_id})"

    # -- mapping onto jax devices -------------------------------------------
    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if _kind_of(d) == self._kind]
        if not devs:
            # graceful fallback: CPU host devices always exist
            devs = jax.devices("cpu")
        return devs[min(self._device_id, len(devs) - 1)]


class CPUPlace(Place):
    _kind = "cpu"

    def __init__(self):
        super().__init__(0)

    def __repr__(self):
        return "Place(cpu)"


class TPUPlace(Place):
    _kind = "tpu"

    def __repr__(self):
        return f"Place(tpu:{self._device_id})"


# Parity alias: code written against the reference uses CUDAPlace for "the
# accelerator"; here the accelerator is the TPU.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace
NPUPlace = TPUPlace


class TPUPinnedPlace(Place):
    """Host-pinned staging buffers; on TPU this is plain host memory."""

    _kind = "cpu"

    def __repr__(self):
        return "Place(tpu_pinned)"


CUDAPinnedPlace = TPUPinnedPlace


def _kind_of(dev: jax.Device) -> str:
    return "tpu" if dev.platform == "tpu" else dev.platform


_current_device: str | None = None


@functools.lru_cache(maxsize=None)
def _has_tpu() -> bool:
    try:
        return len(jax.devices("tpu")) > 0
    except RuntimeError:
        return False


def is_compiled_with_tpu() -> bool:  # parity with is_compiled_with_cuda
    return _has_tpu()


is_compiled_with_cuda = is_compiled_with_tpu
is_compiled_with_xpu = is_compiled_with_tpu


def set_device(device: str):
    """Set the default device, e.g. 'tpu', 'tpu:0', 'cpu'."""
    global _current_device
    name = device.split(":")[0]
    if name == "gpu":
        name = "tpu"  # parity mapping: the accelerator is the TPU
    if name not in ("cpu", "tpu"):
        raise ValueError(f"unsupported device {device!r}; use 'cpu' or 'tpu'")
    _current_device = device.replace("gpu", "tpu")
    return get_device()


def get_device() -> str:
    if _current_device is not None:
        return _current_device
    return "tpu:0" if _has_tpu() else "cpu"


def _default_place() -> Place:
    dev = get_device()
    if dev.startswith("tpu"):
        idx = int(dev.split(":")[1]) if ":" in dev else 0
        return TPUPlace(idx)
    return CPUPlace()


def _place_from_any(place) -> Place:
    if place is None:
        return _default_place()
    if isinstance(place, Place):
        return place
    if isinstance(place, str):
        name = place.split(":")[0]
        idx = int(place.split(":")[1]) if ":" in place else 0
        if name in ("tpu", "gpu", "xpu", "npu"):
            return TPUPlace(idx)
        return CPUPlace()
    if isinstance(place, jax.Device):
        return TPUPlace(place.id) if place.platform == "tpu" else CPUPlace()
    raise TypeError(f"cannot interpret {place!r} as a Place")
