"""Random state management.

The reference keeps stateful per-device generators plus a named
``RNGStatesTracker`` for tensor parallelism
(/root/reference/python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py:23).
JAX RNG is functional (threaded keys), so this module provides:

- a process-global stateful generator facade (``seed``/``next_key``) for the
  eager API, implemented by splitting a root key;
- ``RNGStatesTracker`` with named seed domains — tensor-parallel layers need
  *identical* streams for replicated init and *distinct* streams per model
  shard (e.g. dropout inside a TP region);
- pure helpers to derive keys for use inside jitted/staged code.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np


class Generator:
    """Stateful facade over a functional JAX PRNG key chain.

    The root key is created LAZILY: touching the backend at import time
    would break ``jax.distributed.initialize`` (init_parallel_env must be
    callable after ``import paddle_tpu``, like the reference's
    paddle.distributed.init_parallel_env)."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._key = None
        self._lock = threading.Lock()

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.key(self._seed)
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            return jax.random.key_data(self._key)

    def set_state(self, state):
        with self._lock:
            self._key = jax.random.wrap_key_data(np.asarray(state))


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(s: int):
    """Global seed (parity with paddle.seed)."""
    _default_generator.manual_seed(s)
    return _default_generator


def next_key():
    return _default_generator.next_key()


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


class RNGStatesTracker:
    """Named RNG streams for tensor-parallel regions.

    Parity with the reference's RNGStatesTracker (random.py:23,68): model
    shards register a named seed domain, and ``rng_state(name)`` temporarily
    switches the global generator onto that domain so dropout masks differ (or
    match) across TP ranks by construction.
    """

    def __init__(self):
        self._states = {}

    def reset(self):
        self._states.clear()

    def add(self, name: str, seed_: int):
        if name in self._states:
            raise ValueError(f"rng state {name!r} already exists")
        self._states[name] = Generator(seed_)

    def get_states_tracker(self):
        return {n: g.get_state() for n, g in self._states.items()}

    def set_states_tracker(self, states):
        for n, s in states.items():
            self._states.setdefault(n, Generator(0)).set_state(s)

    @contextlib.contextmanager
    def rng_state(self, name: str = "model_parallel_rng"):
        if name not in self._states:
            raise ValueError(f"rng state {name!r} was not added")
        global _default_generator
        prev = _default_generator
        _default_generator = self._states[name]
        try:
            yield
        finally:
            _default_generator = prev


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def model_parallel_random_seed(seed_: int = 0, mp_rank: int = 0):
    """Seed the global + named TP domains (parity random.py:68)."""
    global_seed = 100 + seed_
    local_seed = seed_ + 1024 + mp_rank * 100
    _tracker.reset()
    seed(global_seed)
    _tracker.add("model_parallel_rng", local_seed)
    _tracker.add("global_seed", global_seed)
