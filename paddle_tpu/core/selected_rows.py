"""Row-sparse gradients — the TPU-native SelectedRows equivalent.

The reference represents an embedding gradient as SelectedRows
(framework/selected_rows.h:1): a (rows, values) pair covering only the
looked-up vocabulary rows, and its sparse Adam updates moments for those
rows only (operators/optimizers/adam_op.h:464, lazy_mode).

TPU-first redesign: everything is STATIC-SHAPED. The row list is the
flattened lookup index tensor (length = batch·seq, duplicates included);
``merged()`` combines duplicates with a fixed-size ``jnp.unique`` padded by
an out-of-range sentinel row, so optimizer updates lower to gather →
per-row math → scatter(mode='drop') — O(touched rows · dim) work and
traffic, never O(vocab · dim).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["RowSparseGrad"]


class RowSparseGrad:
    """(rows, values) gradient for a [num_rows, dim] parameter.

    ``rows``: int array [n] (may contain duplicates); ``values``: [n, dim]
    matching grads. ``rows`` entries equal to ``num_rows`` are padding and
    are dropped by scatter updates.
    """

    __slots__ = ("rows", "values", "num_rows", "_merged", "_mcache")

    def __init__(self, rows, values, num_rows: int, merged: bool = False):
        self.rows = rows
        self.values = values
        self.num_rows = int(num_rows)
        self._merged = merged
        self._mcache = None  # memoized merged() (clip + optimizer both use it)

    # -- Tensor-ish surface (what optimizer/engine code touches) ------------
    @property
    def dtype(self):
        return self.values.dtype

    @property
    def shape(self):
        return (self.num_rows,) + tuple(self.values.shape[1:])

    @property
    def is_sparse_grad(self):
        return True

    def astype(self, dtype):
        return RowSparseGrad(self.rows, self.values.astype(dtype),
                             self.num_rows, self._merged)

    # -- core ops -----------------------------------------------------------
    def merged(self) -> "RowSparseGrad":
        """Combine duplicate rows (static shapes: unique padded with the
        sentinel row ``num_rows``; matching values segment-summed)."""
        if self._merged:
            return self
        if self._mcache is not None:
            return self._mcache
        n = self.rows.shape[0]
        rows = self.rows.astype(jnp.int32)
        uniq = jnp.unique(rows, size=n, fill_value=jnp.int32(self.num_rows))
        seg = jnp.searchsorted(uniq, rows).astype(jnp.int32)
        vals = jax.ops.segment_sum(self.values, seg, num_segments=n)
        self._mcache = RowSparseGrad(uniq, vals, self.num_rows, merged=True)
        return self._mcache

    def to_dense(self):
        z = jnp.zeros(self.shape, self.values.dtype)
        return z.at[self.rows].add(self.values, mode="drop")

    def __add__(self, other):
        if isinstance(other, RowSparseGrad):
            return RowSparseGrad(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]),
                self.num_rows,
            )
        if other is None:
            return self
        # sparse + dense densifies (reference: SelectedRows + LoDTensor sum)
        dense = other._value if hasattr(other, "_value") else other
        return self.to_dense() + dense

    __radd__ = __add__

    def scale(self, coeff):
        return RowSparseGrad(self.rows, self.values * coeff, self.num_rows,
                             self._merged)

    def sq_l2norm(self):
        """Σ values² of the MERGED gradient (for global-norm clipping —
        duplicates must be combined first or the norm overcounts; sentinel
        padding rows are excluded, matching the dense path where masked
        positions contribute zero)."""
        m = self.merged()
        valid = (m.rows < self.num_rows)[:, None].astype(jnp.float32)
        return jnp.sum(jnp.square(m.values.astype(jnp.float32)) * valid)

    def numpy(self):
        return jax.device_get(self.to_dense())

    def __repr__(self):
        return (f"RowSparseGrad(rows={self.rows.shape}, "
                f"values={self.values.shape}, num_rows={self.num_rows})")
