"""Process-level flag registry.

Mirrors the reference's gflags runtime config + python bridge
(/root/reference/paddle/fluid/platform/flags.cc,
/root/reference/paddle/fluid/pybind/global_value_getter_setter.cc): flags are
settable via environment variables ``FLAGS_<name>`` and via
``set_flags``/``get_flags``.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict


class _Flag:
    __slots__ = ("name", "value", "default", "type", "help", "on_change")

    def __init__(self, name, default, help="", on_change=None):
        self.name = name
        self.default = default
        self.type = type(default)
        self.help = help
        self.on_change = on_change
        self.value = self._from_env(default)

    def _from_env(self, default):
        raw = os.environ.get(f"FLAGS_{self.name}")
        if raw is None:
            return default
        return _coerce(raw, self.type)


def _coerce(raw: str, typ) -> Any:
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(raw)
    if typ is float:
        return float(raw)
    return raw


_registry: Dict[str, _Flag] = {}


def define_flag(name: str, default, help: str = "", on_change: Callable | None = None):
    if name in _registry:
        return _registry[name]
    f = _Flag(name, default, help, on_change)
    _registry[name] = f
    return f


def get_flags(names):
    single = isinstance(names, str)
    if single:
        names = [names]
    out = {}
    for n in names:
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _registry:
            raise KeyError(f"unknown flag {n!r}")
        out[f"FLAGS_{key}"] = _registry[key].value
    return out


def set_flags(flags: Dict[str, Any]):
    for n, v in flags.items():
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _registry:
            raise KeyError(f"unknown flag {n!r}")
        f = _registry[key]
        if isinstance(v, str) and f.type is not str:
            v = _coerce(v, f.type)
        f.value = f.type(v) if f.type is not type(None) else v
        if f.on_change is not None:
            f.on_change(f.value)


def flag_value(name: str):
    return _registry[name].value


# ---------------------------------------------------------------------------
# Core flags (parity set from platform/flags.cc; TPU-relevant subset + ours)
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False, "check every op output for NaN/Inf (debug)")
define_flag("benchmark", False, "sync after each op and time (debug/benchmark mode)")
define_flag("eager_delete_tensor_gb", 0.0, "compat no-op: XLA owns memory planning")
define_flag("fraction_of_gpu_memory_to_use", 0.92, "compat no-op on TPU")
define_flag("selected_tpus", "", "restrict visible TPU chips, comma-separated ids")
define_flag("paddle_num_threads", 1, "host-side intra-op threads (compat)")
define_flag("use_pinned_memory", True, "compat: host staging buffers")
define_flag("cudnn_deterministic", False, "compat: request deterministic kernels")
define_flag("tpu_deterministic_ops", False, "request deterministic XLA reductions")
define_flag("call_stack_level", 1, "error message verbosity level")
define_flag("print_op_timings", False, "print per-op timings in eager mode")
define_flag("allocator_strategy", "auto_growth", "compat: XLA/TPU owns allocation")
define_flag("enable_eager_jit_cache", True, "cache jitted callables for hot eager ops")
define_flag("log_level", 0, "VLOG-style verbosity for framework internals")
