"""Named runtime counters — parity with the reference's monitor subsystem
(/root/reference/paddle/fluid/platform/monitor.h:77 StatRegistry,
STAT_ADD/STAT_RESET macros :130).

The reference registers int64 stats (e.g. STAT_gpu0_mem_size) that kernels
bump from C++. Here counters are process-level Python (the hot path is
compiled by XLA, so the useful counters are host-side events: steps run,
bytes fed, retraces, checkpoint writes) with the same add/get/reset surface.
Thread-safe.
"""
from __future__ import annotations

import threading
from typing import Dict

__all__ = ["StatRegistry", "stat_add", "stat_get", "stat_reset",
           "stat_sub", "all_stats"]


class StatRegistry:
    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, int] = {}

    @classmethod
    def instance(cls) -> "StatRegistry":
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    def add(self, name: str, value: int = 1) -> int:
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + int(value)
            return self._stats[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._stats.get(name, 0)

    def reset(self, name: str) -> None:
        with self._lock:
            self._stats[name] = 0

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)


def stat_add(name: str, value: int = 1) -> int:
    """STAT_ADD parity (monitor.h:130)."""
    return StatRegistry.instance().add(name, value)


def stat_sub(name: str, value: int = 1) -> int:
    return StatRegistry.instance().add(name, -value)


def stat_get(name: str) -> int:
    return StatRegistry.instance().get(name)


def stat_reset(name: str) -> None:
    StatRegistry.instance().reset(name)


def all_stats() -> Dict[str, int]:
    return StatRegistry.instance().snapshot()
