"""FLAGS_check_nan_inf inside COMPILED steps.

The reference instruments every executor so the flag catches NaN/Inf where
real training runs (paddle/fluid/framework/details/nan_inf_utils_detail.cc
sweeps each op's outputs per step). Under XLA the step is one compiled
program, so the TPU-native equivalent is a post-step finite sweep: when the
flag is set at BUILD time, the jitted step computes an `isfinite().all()`
flag per loss/grad/param leaf (cheap fused reduces, stacked into one bool
vector so the host fetches a single tiny array) and the host raises a
`FloatingPointError` naming the offending tensors.

The flag is snapshotted when the compiled step is BUILT (same policy as the
static-graph AMP snapshot, static/program.py): flipping it later does not
retroactively instrument an already-compiled step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .flags import flag_value

__all__ = ["jit_check_enabled", "finite_flags", "raise_if_nonfinite"]


def jit_check_enabled() -> bool:
    """Read FLAGS_check_nan_inf at compiled-step build time."""
    return bool(flag_value("check_nan_inf"))


def _float_leaf(leaf) -> bool:
    return hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.inexact)


def finite_flags(names_out: list, **groups):
    """Trace-time sweep: one `isfinite().all()` per floating leaf.

    ``groups`` maps a prefix (e.g. ``grad``) to a pytree. Appends the leaf
    names to ``names_out`` (a mutable list captured by the caller — filled
    during tracing, read back on the host after execution) and returns the
    stacked bool vector, or None when nothing to check.
    """
    names_out.clear()
    flags = []
    for gname, tree in groups.items():
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        for path, leaf in flat:
            if _float_leaf(leaf):
                names_out.append(f"{gname}{jax.tree_util.keystr(path)}")
                flags.append(jnp.isfinite(leaf).all())
    return jnp.stack(flags) if flags else None


def raise_if_nonfinite(names, flags):
    """Host side: fetch the flag vector (one tiny transfer) and raise a
    located error listing every non-finite tensor."""
    if flags is None:
        return
    ok = np.asarray(flags)
    if ok.all():
        return
    bad = [n for n, f in zip(names, ok) if not f]
    shown = ", ".join(bad[:8]) + (f" (+{len(bad) - 8} more)" if len(bad) > 8
                                  else "")
    raise FloatingPointError(
        f"FLAGS_check_nan_inf: NaN or Inf detected in compiled step: {shown}")
