"""FLAGS_check_nan_inf inside COMPILED steps.

The reference instruments every executor so the flag catches NaN/Inf where
real training runs (paddle/fluid/framework/details/nan_inf_utils_detail.cc
sweeps each op's outputs per step). Under XLA the step is one compiled
program, so the TPU-native equivalent is a post-step finite sweep: when the
flag is set at BUILD time, the jitted step computes an `isfinite().all()`
flag per loss/grad/param leaf (cheap fused reduces, stacked into one bool
vector so the host fetches a single tiny array) and the host raises a
`FloatingPointError` naming the offending tensors.

The flag is snapshotted when the compiled step is BUILT (same policy as the
static-graph AMP snapshot, static/program.py): flipping it later does not
retroactively instrument an already-compiled step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .flags import flag_value

__all__ = ["jit_check_enabled", "finite_flags", "finite_report",
           "raise_if_nonfinite", "select_if_finite",
           "tree_fingerprint", "zero_fingerprint"]


def jit_check_enabled() -> bool:
    """Read FLAGS_check_nan_inf at compiled-step build time."""
    return bool(flag_value("check_nan_inf"))


def _float_leaf(leaf) -> bool:
    return hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.inexact)


def finite_flags(names_out: list, **groups):
    """Trace-time sweep: one `isfinite().all()` per floating leaf.

    ``groups`` maps a prefix (e.g. ``grad``) to a pytree. Appends the leaf
    names to ``names_out`` (a mutable list captured by the caller — filled
    during tracing, read back on the host after execution) and returns the
    stacked bool vector, or None when nothing to check.
    """
    names_out.clear()
    flags = []
    for gname, tree in groups.items():
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        for path, leaf in flat:
            if _float_leaf(leaf):
                names_out.append(f"{gname}{jax.tree_util.keystr(path)}")
                flags.append(jnp.isfinite(leaf).all())
    return jnp.stack(flags) if flags else None


def select_if_finite(flags, new_tree, old_tree):
    """Trace-time guard half (resilience ``guard_updates`` contract):
    when ANY flag in the sweep is False, every leaf of ``new_tree`` is
    replaced by its ``old_tree`` twin — the compiled step returns the
    incoming state unchanged, i.e. a non-finite step never applies its
    update. Composes with buffer donation (XLA aliases whichever side
    the select keeps)."""
    ok = jnp.all(flags)
    return jax.tree_util.tree_map(lambda a, b: jnp.where(ok, a, b),
                                  new_tree, old_tree)


def _xor_fold_leaf(leaf):
    """XOR-fold one array leaf to a single uint32, bit-exactly: every
    flipped bit in the leaf flips the result. The bitcast preserves the
    leaf's raw representation (no value rounding), so two states that
    differ by ONE mantissa bit — the silent-corruption case a float
    tolerance would wave through — fold to different words."""
    x = leaf
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        x = jnp.concatenate([jnp.real(x).ravel(), jnp.imag(x).ravel()])
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    size = jnp.dtype(x.dtype).itemsize
    if size == 1:
        u = jax.lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.uint32)
    elif size == 2:
        u = jax.lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
    else:
        # 4-byte dtypes map 1:1; 8-byte dtypes gain a trailing dim of 2
        # 32-bit words — folded like any other axis
        u = jax.lax.bitcast_convert_type(x, jnp.uint32)
    return jax.lax.reduce(u, np.uint32(0), jax.lax.bitwise_xor,
                          tuple(range(u.ndim)))


def zero_fingerprint():
    """The fingerprint aval twin ``tree_fingerprint`` returns — the
    not-computed branch of the in-jit ``lax.cond`` gate must produce the
    same structure and dtypes."""
    return {"sum": jnp.zeros((), jnp.float32),
            "abs_sum": jnp.zeros((), jnp.float32),
            "xor": jnp.zeros((), jnp.uint32)}


def tree_fingerprint(*trees):
    """Trace-time state fingerprint: fold every leaf of the given
    pytrees into three scalars — a float32 sum, a float32 abs-sum, and a
    bit-exact uint32 XOR word (``_xor_fold_leaf`` per leaf, rotated into
    the accumulator so leaf order matters).

    Runs INSIDE a compiled step: a handful of fused reduces over state
    already resident in HBM, returning scalars the host can fetch
    without materializing anything large. Deterministic for a fixed
    compiled program, so two DP replicas executing the same program on
    the same values produce bit-identical fingerprints — any
    disagreement is divergence (see ``resilience.integrity``). Float
    leaves contribute to all three folds; integer/bool leaves contribute
    to the XOR word only (their sum has no shared float carrier).
    """
    total = jnp.zeros((), jnp.float32)
    abs_total = jnp.zeros((), jnp.float32)
    xor_total = jnp.zeros((), jnp.uint32)
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            if not hasattr(leaf, "dtype"):
                leaf = jnp.asarray(leaf)
            if _float_leaf(leaf):
                f = leaf.astype(jnp.float32)
                total = total + jnp.sum(f)
                abs_total = abs_total + jnp.sum(jnp.abs(f))
            # rotate-then-xor: identical twin leaves at different tree
            # positions cannot cancel to 0 the way a plain XOR chain would
            xor_total = ((xor_total << 1) | (xor_total >> 31)) \
                ^ _xor_fold_leaf(leaf)
    return {"sum": total, "abs_sum": abs_total, "xor": xor_total}


def finite_report(names, flags):
    """Host side of the sweep: fetch the tiny flag vector and name the
    non-finite leaves. Returns ``(ok, bad_names)``; ``flags is None``
    (nothing to check) is ok. Shared by ``raise_if_nonfinite`` and the
    resilience StepGuard so the two readings can never drift."""
    if flags is None:
        return True, []
    ok = np.asarray(flags)
    if ok.all():
        return True, []
    return False, [n for n, f in zip(names, ok) if not f]


def raise_if_nonfinite(names, flags, loss_scale=None):
    """Host side: fetch the flag vector (one tiny transfer) and raise a
    located error listing every non-finite tensor, the loss scale in
    effect (when an AMP scaler exists — scale 65536 with fp16 says
    "overflow", scale 1.0 says "model/data"), and the recovery hint.
    Leaves a ``resilience/nonfinite_steps`` telemetry trace even on
    un-guarded paths that die right after."""
    all_ok, bad = finite_report(names, flags)
    if all_ok:
        return
    from ..profiler.telemetry import get_telemetry

    get_telemetry().counter("resilience/nonfinite_steps")
    shown = ", ".join(bad[:8]) + (f" (+{len(bad) - 8} more)" if len(bad) > 8
                                  else "")
    if loss_scale is None:
        from ..amp.grad_scaler import current_loss_scale

        loss_scale = current_loss_scale()
    scale_note = (f" (loss_scale={float(loss_scale):g})"
                  if loss_scale is not None else "")
    raise FloatingPointError(
        f"FLAGS_check_nan_inf: NaN or Inf detected in compiled step: "
        f"{shown}{scale_note}. For skip/rollback recovery instead of "
        f"aborting, wrap the step in paddle_tpu.resilience.StepGuard "
        f"(engine arg guard_updates=True).")
