"""Dtype model for the TPU-native framework.

Mirrors the capability of the reference's VarType dtype enum
(/root/reference/paddle/fluid/framework/framework.proto:106) but maps directly
onto numpy/JAX dtypes — on TPU, bfloat16 is first-class and the MXU prefers
bf16/f32, so the default policy favors float32 with easy bf16 casting.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical name -> numpy dtype. bfloat16 comes from ml_dtypes (jax's backing).
_NAME_TO_DTYPE = {
    "bool": np.dtype(np.bool_),
    "uint8": np.dtype(np.uint8),
    "int8": np.dtype(np.int8),
    "int16": np.dtype(np.int16),
    "int32": np.dtype(np.int32),
    "int64": np.dtype(np.int64),
    "float16": np.dtype(np.float16),
    "bfloat16": np.dtype(ml_dtypes.bfloat16),
    "float32": np.dtype(np.float32),
    "float64": np.dtype(np.float64),
    "complex64": np.dtype(np.complex64),
    "complex128": np.dtype(np.complex128),
    "float8_e4m3fn": np.dtype(ml_dtypes.float8_e4m3fn),
    "float8_e5m2": np.dtype(ml_dtypes.float8_e5m2),
}

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
}

bool_ = _NAME_TO_DTYPE["bool"]
uint8 = _NAME_TO_DTYPE["uint8"]
int8 = _NAME_TO_DTYPE["int8"]
int16 = _NAME_TO_DTYPE["int16"]
int32 = _NAME_TO_DTYPE["int32"]
int64 = _NAME_TO_DTYPE["int64"]
float16 = _NAME_TO_DTYPE["float16"]
bfloat16 = _NAME_TO_DTYPE["bfloat16"]
float32 = _NAME_TO_DTYPE["float32"]
float64 = _NAME_TO_DTYPE["float64"]
complex64 = _NAME_TO_DTYPE["complex64"]
complex128 = _NAME_TO_DTYPE["complex128"]
float8_e4m3fn = _NAME_TO_DTYPE["float8_e4m3fn"]
float8_e5m2 = _NAME_TO_DTYPE["float8_e5m2"]

_default_dtype = float32


def convert_dtype(dtype) -> np.dtype:
    """Normalize any user-supplied dtype spec to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name in _NAME_TO_DTYPE:
            return _NAME_TO_DTYPE[name]
        return np.dtype(name)
    if isinstance(dtype, np.dtype):
        return dtype
    # jnp.float32-style scalar types, python types, ml_dtypes types
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    return convert_dtype(dtype).name


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if d.name not in ("float16", "bfloat16", "float32", "float64"):
        raise TypeError(
            "set_default_dtype only supports float16/bfloat16/float32/float64, "
            f"got {d.name}"
        )
    _default_dtype = d


def get_default_dtype() -> np.dtype:
    return _default_dtype


def is_floating_point(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.floating)


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.integer)


def is_complex(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.complexfloating)
