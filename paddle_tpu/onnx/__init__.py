"""paddle.onnx — ONNX export surface (parity:
/root/reference/python/paddle/onnx/export.py, which delegates to the
external ``paddle2onnx`` package).

Neither ``onnx`` nor a converter is present in this image, and this
framework's native interchange format is StableHLO (``jit.save`` writes a
self-contained AOT artifact any XLA runtime loads). ``export`` therefore
raises with that guidance unless an ``onnx`` toolchain is importable —
the gate mirrors the reference, which also hard-depends on an external
package for this API.
"""
from __future__ import annotations

import importlib.util

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export ``layer`` to ONNX at ``path``.

    Requires an ONNX toolchain in the environment. Without one, use
    ``paddle_tpu.jit.save(layer, path, input_spec=...)`` — the ``.pdexport``
    StableHLO artifact is this framework's portable serving format (served
    by the Python/C/Go clients).
    """
    if importlib.util.find_spec("onnx") is None:
        raise ModuleNotFoundError(
            "paddle_tpu.onnx.export requires the 'onnx' package, which is "
            "not installed in this environment. The TPU-native portable "
            "artifact is StableHLO: paddle_tpu.jit.save(layer, path, "
            "input_spec=[...]) produces a .pdexport any XLA runtime serves.")
    raise NotImplementedError(
        "ONNX conversion from StableHLO is not implemented; serve the "
        "jit.save .pdexport artifact instead.")
